"""Ring attention (context parallelism) + transformer LM.

Distributed tests run on the 8-device virtual CPU mesh (SURVEY.md §4.6
strategy — the in-process pserver analog).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core import place
from paddle_tpu.models import transformer
from paddle_tpu.parallel import ring


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, rng, causal):
        mesh = place.make_mesh((2, 4), (place.AXIS_DATA, place.AXIS_SEQ))
        B, T, H, D = 4, 16, 2, 8
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        lens = jnp.asarray(np.array([16, 9, 12, 5], np.int32))
        got = ring.ring_attention_spmd(q, k, v, mesh, causal=causal,
                                       lengths=lens)
        want = ring.full_attention(q, k, v, causal=causal, lengths=lens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_full_attention(self, rng):
        mesh = place.make_mesh((1, 8), (place.AXIS_DATA, place.AXIS_SEQ))
        B, T, H, D = 2, 16, 2, 4
        q = rng.randn(B, T, H, D).astype(np.float32)
        k = rng.randn(B, T, H, D).astype(np.float32)
        v = rng.randn(B, T, H, D).astype(np.float32)

        def loss_ring(q_, k_, v_):
            return jnp.sum(ring.ring_attention_spmd(
                jnp.asarray(q_), jnp.asarray(k_), jnp.asarray(v_), mesh,
                causal=True) ** 2)

        def loss_full(q_, k_, v_):
            return jnp.sum(ring.full_attention(
                jnp.asarray(q_), jnp.asarray(k_), jnp.asarray(v_),
                causal=True) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_full):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5)

    def test_inside_jit(self, rng):
        mesh = place.make_mesh((2, 4), (place.AXIS_DATA, place.AXIS_SEQ))
        B, T, H, D = 2, 8, 1, 4
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))

        @jax.jit
        def f(q_):
            return ring.ring_attention_spmd(q_, q_, q_, mesh, causal=True)

        out = f(q)
        want = ring.full_attention(q, q, q, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


CFG = transformer.TransformerConfig(vocab=50, d_model=32, n_heads=4,
                                    n_layers=2, d_ff=64, max_len=32,
                                    dtype=jnp.float32)


class TestTransformer:
    def test_forward_shapes_and_determinism(self, rng):
        params = transformer.init_params(jax.random.PRNGKey(0), CFG)
        toks = jnp.asarray(rng.randint(0, 50, (2, 16)).astype(np.int32))
        a = transformer.forward(params, toks, CFG)
        b = transformer.forward(params, toks, CFG)
        assert a.shape == (2, 16, 50)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_lm_learns(self, rng):
        params = transformer.init_params(jax.random.PRNGKey(0), CFG)
        B, T = 8, 16
        # learnable pattern: token t+1 = (token t + 1) % vocab
        start = rng.randint(0, 50, (B, 1))
        toks = (start + np.arange(T)[None, :]) % 50
        tgt = (toks + 1) % 50
        toks, tgt = jnp.asarray(toks, jnp.int32), jnp.asarray(tgt, jnp.int32)

        step = jax.jit(jax.value_and_grad(
            lambda p: transformer.lm_loss(p, toks, tgt, CFG)))
        vals, hist = params, []
        for _ in range(30):
            l, g = step(vals)
            vals = jax.tree_util.tree_map(lambda p, gr: p - 0.1 * gr, vals, g)
            hist.append(float(l))
        assert hist[-1] < hist[0] * 0.5, (hist[0], hist[-1])

    @pytest.mark.slow
    def test_spmd_dp_sp_tp_matches_single_device(self, rng):
        """The full 3-axis GSPMD train step must reproduce single-device
        numerics — DP over batch, ring-attention CP over seq, TP over
        heads/MLP.

        `slow`: one of the two observed crash sites of the full-sweep
        XLA:CPU `backend_compile` segfault — see the root-cause account
        on test_ring_matches_full_and_kv_grads_grouped below. The
        grad-of-shard_map compile here (line "g_got = ...") is where
        the 2026-08-07 sweep died."""
        cfg = transformer.TransformerConfig(
            vocab=50, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_len=32, dtype=jnp.float32, use_ring_attention=True)
        mesh = place.make_mesh(
            (2, 2, 2), (place.AXIS_DATA, place.AXIS_SEQ, place.AXIS_MODEL))
        params = transformer.init_params(jax.random.PRNGKey(1), cfg)
        shardings = transformer.param_shardings(cfg, mesh)
        sharded = jax.tree_util.tree_map(jax.device_put, params, shardings)
        B, T = 4, 16
        toks = jnp.asarray(rng.randint(0, 50, (B, T)).astype(np.int32))
        tgt = jnp.asarray(rng.randint(0, 50, (B, T)).astype(np.int32))
        lens = jnp.asarray(np.array([16, 10, 16, 7], np.int32))

        ref_cfg = dataclasses.replace(cfg, use_ring_attention=False)
        ref = transformer.lm_loss(params, toks, tgt, ref_cfg, lengths=lens)

        @jax.jit
        def dist_loss(p, tk, tg, ln):
            return transformer.lm_loss(p, tk, tg, cfg, mesh=mesh, lengths=ln)

        got = dist_loss(sharded, toks, tgt, lens)
        np.testing.assert_allclose(float(got), float(ref), rtol=2e-5)

        # grads too: the backward collectives must be correct
        g_ref = jax.grad(lambda p: transformer.lm_loss(
            p, toks, tgt, ref_cfg, lengths=lens))(params)
        g_got = jax.jit(jax.grad(lambda p: transformer.lm_loss(
            p, toks, tgt, cfg, mesh=mesh, lengths=lens)))(sharded)
        ref_flat = jax.tree_util.tree_leaves(g_ref)
        got_flat = jax.tree_util.tree_leaves(g_got)
        for a, b in zip(ref_flat, got_flat):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-3, atol=1e-4)


class TestRingFlashAttention:
    """Ring CP composed with the Pallas flash kernel as the block engine
    (interpret mode on the CPU mesh; the same code path drives the real
    kernel on TPU)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, rng, causal):
        mesh = place.make_mesh((2, 4), (place.AXIS_DATA, place.AXIS_SEQ))
        B, T, H, D = 2, 32, 2, 8
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        got = ring.ring_attention_spmd(q, k, v, mesh, causal=causal,
                                       use_flash=True, interpret=True)
        want = ring.full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_full_attention(self, rng, causal):
        mesh = place.make_mesh((1, 4), (place.AXIS_DATA, place.AXIS_SEQ))
        B, T, H, D = 2, 16, 2, 4
        q = rng.randn(B, T, H, D).astype(np.float32)
        k = rng.randn(B, T, H, D).astype(np.float32)
        v = rng.randn(B, T, H, D).astype(np.float32)

        def loss_ring(q_, k_, v_):
            return jnp.sum(ring.ring_attention_spmd(
                jnp.asarray(q_), jnp.asarray(k_), jnp.asarray(v_), mesh,
                causal=causal, use_flash=True, interpret=True) ** 2)

        def loss_full(q_, k_, v_):
            return jnp.sum(ring.full_attention(
                jnp.asarray(q_), jnp.asarray(k_), jnp.asarray(v_),
                causal=causal) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g_ring, g_full):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5,
                                       err_msg=f"d{name}")

    def test_rejects_ragged_lengths(self, rng):
        mesh = place.make_mesh((1, 4), (place.AXIS_DATA, place.AXIS_SEQ))
        x = jnp.zeros((2, 16, 2, 4), jnp.float32)
        with pytest.raises(ValueError, match="packed equal-length"):
            ring.ring_attention_spmd(
                x, x, x, mesh, use_flash=True,
                lengths=jnp.asarray([16, 9], jnp.int32))

    def test_causal_bwd_outlier_no_nan(self, rng):
        """Gradient NaN regression: queries aligning far more strongly
        with FUTURE-shard keys than any allowed key make p = exp(s − lse)
        overflow if the excluded block is zeroed after the kernel instead
        of masked inside the exponent."""
        mesh = place.make_mesh((1, 4), (place.AXIS_DATA, place.AXIS_SEQ))
        B, T, H, D = 1, 16, 1, 4
        u = np.ones((D,), np.float32)
        q = np.tile(u * 20, (B, T, H, 1)).astype(np.float32)
        k = rng.randn(B, T, H, D).astype(np.float32) * 0.01
        k[:, 12:] = u * 20          # future shard for most queries
        v = rng.randn(B, T, H, D).astype(np.float32)

        def loss(fn):
            def f(q_, k_, v_):
                return jnp.sum(fn(jnp.asarray(q_), jnp.asarray(k_),
                                  jnp.asarray(v_)) ** 2)
            return f

        ring_fn = lambda a, b, c: ring.ring_attention_spmd(
            a, b, c, mesh, causal=True, use_flash=True, interpret=True)
        full_fn = lambda a, b, c: ring.full_attention(a, b, c, causal=True)
        g_ring = jax.grad(loss(ring_fn), argnums=(0, 1, 2))(q, k, v)
        g_full = jax.grad(loss(full_fn), argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g_ring, g_full):
            assert np.isfinite(np.asarray(a)).all(), f"d{name} has NaN/inf"
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5,
                                       err_msg=f"d{name}")


class TestGQAEngines:
    """GQA (Hkv < H) handled INSIDE the attention engines: the ring
    collectives must rotate Hkv-head K/V, and gradients w.r.t. k/v must
    come back at Hkv heads (group-summed), matching the explicitly
    repeated MHA formulation numerically."""

    def _qkv(self, rng, B, T, H, Hkv, D):
        q = rng.randn(B, T, H, D).astype(np.float32)
        k = rng.randn(B, T, Hkv, D).astype(np.float32)
        v = rng.randn(B, T, Hkv, D).astype(np.float32)
        return q, k, v

    def _repeat(self, x, g):
        return np.repeat(x, g, axis=2)

    def test_full_attention_grouped_matches_repeat(self, rng):
        B, T, H, Hkv, D = 2, 12, 4, 2, 8
        q, k, v = self._qkv(rng, B, T, H, Hkv, D)
        lens = jnp.asarray([12, 7], jnp.int32)
        got = ring.full_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), causal=True, lengths=lens)
        want = ring.full_attention(
            jnp.asarray(q), jnp.asarray(self._repeat(k, 2)),
            jnp.asarray(self._repeat(v, 2)), causal=True, lengths=lens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow
    @pytest.mark.parametrize("use_flash", [False, True])
    def test_ring_matches_full_and_kv_grads_grouped(self, rng, use_flash):
        """Ring GQA fwd + grouped dk/dv grads match the head-repeated
        MHA formulation (full_attention with an explicit repeat whose
        adjoint group-sums).

        `slow` — root-cause findings on the full-sweep XLA:CPU
        `backend_compile` segfault (ROADMAP housekeeping flag from
        PR 15, investigated PR 16): when the tier-1 sweep reaches this
        file at ~80% (~750 s, ~700 tests of jitted programs resident),
        the process dies with SIGSEGV *inside* XLA:CPU compilation of
        whichever of this file's big reverse-mode shard_map programs
        compiles first — PR 15 observed it here, the 2026-08-07 sweep
        died earlier in the file at test_spmd_dp_sp_tp_matches_
        single_device (faulthandler: `jax/_src/compiler.py:307
        backend_compile` under `_scan_transpose`, no repo frame below
        jax). It is NOT this test's code and not any single suite's
        state: both parametrizations pass in isolation (~30 s), after
        the full serving/fleet block (160 tests, one process), and
        after the master/distributed/elastic block (121 tests —
        including the six leaked `MasterService._snapshot_loop` /
        `_beat` daemon threads visible in the crash dump; threads
        exonerated). Host memory is not a factor (128 GB free, 1-core
        host, 8 simulated XLA host devices, jax 0.4.37). Everything
        points at process state accumulated over the FULL sweep
        (hundreds of live LLVM-JIT'd executables) tripping a bug in
        XLA:CPU's compiler on these largest-in-repo grad programs —
        environmental, not reachable from repo code. Marked `slow`
        (with the spmd test above, the other observed crash site) so
        the fast tier stops dying at 80% and the ~18% of the suite
        after this file gets coverage; the slow tier and isolation
        runs still execute both."""
        B, T, H, Hkv, D = 2, 16, 4, 2, 4
        q, k, v = self._qkv(rng, B, T, H, Hkv, D)

        def loss_ring(q_, k_, v_):
            return jnp.sum(ring.ring_attention_spmd(
                jnp.asarray(q_), jnp.asarray(k_), jnp.asarray(v_), mesh,
                causal=True, use_flash=use_flash, interpret=True) ** 2)

        def loss_full(q_, k_, v_):
            return jnp.sum(ring.full_attention(
                jnp.asarray(q_), jnp.asarray(self._repeat(k_, 2)),
                jnp.asarray(self._repeat(v_, 2)), causal=True) ** 2)

        got = ring.ring_attention_spmd(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh,
            causal=True, use_flash=use_flash, interpret=True)
        want = ring.full_attention(
            jnp.asarray(q), jnp.asarray(self._repeat(k, 2)),
            jnp.asarray(self._repeat(v, 2)), causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        # autodiff folds the repeat's adjoint, so g_full's dk/dv are
        # already the group-sum at Hkv heads — directly comparable
        g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        assert g_ring[1].shape == (B, T, Hkv, D)
        assert g_full[1].shape == (B, T, Hkv, D)
        for name, a, b in zip("qkv", g_ring, g_full):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5,
                                       err_msg=f"d{name}")


class TestMoETransformer:
    """moe_experts>0: the FFN is an expert-parallel top-k MoE
    (parallel/moe.moe_ffn) with the load-balance aux loss threaded into
    lm_loss; the dense path keeps its exact behavior."""

    MOE_CFG = transformer.TransformerConfig(
        vocab=50, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=32,
        dtype=jnp.float32, moe_experts=4, moe_capacity_factor=4.0)

    def test_single_expert_matches_dense(self, rng):
        """E=1 with weights copied from the dense mlp must reproduce the
        dense forward exactly (gate softmax over one expert = 1)."""
        cfg1 = dataclasses.replace(self.MOE_CFG, moe_experts=1,
                                   moe_capacity_factor=64.0)
        dense = transformer.init_params(jax.random.PRNGKey(0), CFG)
        p1 = transformer.init_params(jax.random.PRNGKey(0), cfg1)
        p1["embed"] = dense["embed"]
        p1["pos"] = dense["pos"]
        p1["ln_f"], p1["ln_f_b"] = dense["ln_f"], dense["ln_f_b"]
        for k in ("ln1", "ln1_b", "qkv", "attn_out", "ln2", "ln2_b"):
            p1["blocks"][k] = dense["blocks"][k]
        p1["blocks"]["moe_w_in"] = dense["blocks"]["mlp_in"][:, None]
        p1["blocks"]["moe_w_out"] = dense["blocks"]["mlp_out"][:, None]
        toks = jnp.asarray(rng.randint(0, 50, (2, 16)).astype(np.int32))
        a = transformer.forward(dense, toks, CFG)
        b = transformer.forward(p1, toks, cfg1)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)

    def test_moe_lm_learns_with_aux(self, rng):
        cfg = self.MOE_CFG
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        logits, aux = transformer.forward(
            params, jnp.zeros((2, 8), jnp.int32), cfg, return_aux=True)
        assert float(aux) > 0       # balance loss present
        B, T = 8, 16
        start = rng.randint(0, 50, (B, 1))
        toks = (start + np.arange(T)[None, :]) % 50
        tgt = (toks + 1) % 50
        toks = jnp.asarray(toks, jnp.int32)
        tgt = jnp.asarray(tgt, jnp.int32)
        step = jax.jit(jax.value_and_grad(
            lambda p: transformer.lm_loss(p, toks, tgt, cfg)))
        vals, hist = params, []
        for _ in range(30):
            l, g = step(vals)
            vals = jax.tree_util.tree_map(lambda p, gr: p - 0.1 * gr,
                                          vals, g)
            hist.append(float(l))
        assert hist[-1] < hist[0] * 0.6, (hist[0], hist[-1])

    def test_ep_sharded_train_step(self, rng):
        """Experts sharded over the expert axis: param_shardings apply
        and the jitted train step runs under GSPMD."""
        mesh = place.make_mesh((2, 4),
                               (place.AXIS_DATA, place.AXIS_EXPERT))
        cfg = self.MOE_CFG
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        sh = transformer.param_shardings(cfg, mesh)
        params = jax.tree_util.tree_map(jax.device_put, params, sh)
        toks = jnp.asarray(rng.randint(0, 50, (4, 16)).astype(np.int32))
        tgt = jnp.asarray(rng.randint(0, 50, (4, 16)).astype(np.int32))

        @jax.jit
        def step(p):
            return jax.value_and_grad(
                lambda p_: transformer.lm_loss(p_, toks, tgt, cfg,
                                               mesh=mesh))(p)

        l, g = step(params)
        assert np.isfinite(float(l))
        chex = jax.tree_util.tree_structure(g)
        assert chex == jax.tree_util.tree_structure(params)

    def test_moe_decode_matches_forward(self, rng):
        """KV-cache decode with the MoE FFN reproduces the full forward
        (decode capacity = batch, so no token drops at inference)."""
        cfg = dataclasses.replace(self.MOE_CFG, d_model=16, n_heads=2,
                                  d_ff=32, max_len=24)
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        B, T = 2, 8
        toks = jnp.asarray(rng.randint(0, 50, (B, T)).astype(np.int32))
        want = transformer.forward(params, toks, cfg)
        cache = transformer.init_cache(cfg, B, 16)
        for t in range(T):
            logits, cache = transformer.decode_step(
                params, cache, toks[:, t], jnp.asarray(t, jnp.int32),
                cfg)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(want[:, t]), rtol=2e-4,
                atol=2e-4)

    def test_moe_composes_with_layer_remat(self, rng):
        """MoE FFN + layer-granular stash remat: q8_remat's vjp covers
        every block output generically (the aux scalar included), so the
        capacity lever composes with the expert family.

        What the old assert got wrong (it was the last env-sensitive
        tier-1 flake): it bounded the PER-LEAF max relative error of the
        q8 grads at 0.05, but the q8 backward linearizes each block at
        x̃ = dequant(stash), and a stash perturbation (≤ 0.5/127 of the
        tensor absmax, ops/q8.py) can flip a near-tie top-k ROUTING
        decision in the recomputed gate — an O(1), perfectly correct
        divergence on the few affected rows whose magnitude depends on
        backend rounding. Deterministic restructure:

        1. the remat/MoE COMPOSITION machinery (every output's cotangent
           threaded, aux edge included) is checked on the bf16 stash,
           whose ~2^-9 cast noise cannot flip routing at these margins;
        2. the q8 stash is checked with a GLOBAL metric (relative L2
           over the concatenated grads + descent-direction cosine) whose
           tolerance is derived from the documented stash noise: a few
           flipped tokens among B*T=64 move the global L2 by O(k/64),
           not O(1), while a broken vjp (dropped edge, zeroed cotangent)
           still fails by orders of magnitude."""
        cfg_d = dataclasses.replace(self.MOE_CFG)
        params = transformer.init_params(jax.random.PRNGKey(0), cfg_d)
        toks = jnp.asarray(rng.randint(0, 50, (4, 16)).astype(np.int32))
        tgt = jnp.asarray(rng.randint(0, 50, (4, 16)).astype(np.int32))

        def grad_of(cfg):
            return jax.value_and_grad(
                lambda p: transformer.lm_loss(p, toks, tgt, cfg))(params)

        def flat(g):
            return jnp.concatenate(
                [l.reshape(-1).astype(jnp.float32)
                 for l in jax.tree_util.tree_leaves(g)])

        ld, gd = grad_of(cfg_d)
        fd = flat(gd)

        # (1) machinery, deterministically: bf16 stash. The PER-LEAF
        # check survives here (it would catch a vjp regression confined
        # to a small leaf, e.g. a zeroed gate cotangent, that a global
        # metric dilutes away) — bf16's tiny cast noise makes it stable.
        lb, gb = grad_of(dataclasses.replace(self.MOE_CFG, remat="bf16"))
        np.testing.assert_allclose(float(ld), float(lb), rtol=1e-6)
        fb = flat(gb)
        rel_l2_b = float(jnp.linalg.norm(fb - fd)
                         / (jnp.linalg.norm(fd) + 1e-12))
        assert rel_l2_b < 0.02, f"bf16 remat grad divergence {rel_l2_b}"
        worst_leaf = max(
            float(jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(b)) + 1e-8))
            for a, b in zip(jax.tree_util.tree_leaves(gb),
                            jax.tree_util.tree_leaves(gd)))
        assert worst_leaf < 0.05, f"bf16 per-leaf divergence {worst_leaf}"

        # (2) q8 stash: forward exact, backward within the noise budget
        lr, gr = grad_of(dataclasses.replace(self.MOE_CFG, remat="q8"))
        np.testing.assert_allclose(float(ld), float(lr), rtol=1e-6)
        fr = flat(gr)
        rel_l2 = float(jnp.linalg.norm(fr - fd)
                       / (jnp.linalg.norm(fd) + 1e-12))
        cos = float(jnp.dot(fr, fd)
                    / (jnp.linalg.norm(fr) * jnp.linalg.norm(fd) + 1e-12))
        # budget: per-block linearization offset ≤ 0.5/127 (≈0.4%) of
        # the block input's absmax, amplified through 2 blocks' worth of
        # nonlinearities plus worst-case routing flips on a handful of
        # the 64 tokens — two orders of magnitude below a broken-vjp
        # failure (rel_l2 ~ 1, cos ~ 0)
        assert rel_l2 < 0.30, f"q8 remat global grad divergence {rel_l2}"
        assert cos > 0.95, f"q8 remat grads left the descent cone: {cos}"


class TestGenerate:
    CFG = transformer.TransformerConfig(
        vocab=50, d_model=16, n_layers=2, n_heads=2, d_ff=32, max_len=24,
        dtype=jnp.float32)

    def test_decode_matches_forward_teacher_forcing(self, rng):
        """KV-cache incremental decode must reproduce the full forward's
        logits position by position (the correctness bar for any cache)."""
        cfg = self.CFG
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        B, T = 2, 8
        toks = jnp.asarray(rng.randint(0, cfg.vocab, (B, T)), jnp.int32)
        full = transformer.forward(params, toks, cfg)        # [B, T, V]
        cache = transformer.init_cache(cfg, B, T)
        for t in range(T):
            step_logits, cache = transformer.decode_step(
                params, cache, toks[:, t], jnp.asarray(t, jnp.int32), cfg)
            np.testing.assert_allclose(
                np.asarray(step_logits), np.asarray(full[:, t]),
                rtol=2e-4, atol=2e-4, err_msg=f"position {t}")

    def test_prefill_matches_forward_last_position(self, rng):
        cfg = self.CFG
        params = transformer.init_params(jax.random.PRNGKey(1), cfg)
        B, T = 2, 6
        toks = jnp.asarray(rng.randint(0, cfg.vocab, (B, T)), jnp.int32)
        full = transformer.forward(params, toks, cfg)
        logits, cache = transformer.prefill(params, toks, cfg, T + 4)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, -1]),
                                   rtol=2e-4, atol=2e-4)
        assert cache["k"].shape == (2, B, T + 4, 2, 8)

    def test_greedy_generate_matches_stepwise_argmax(self, rng):
        """generate(temperature=0) must equal the naive loop that reruns
        the full forward and takes argmax each step."""
        cfg = self.CFG
        params = transformer.init_params(jax.random.PRNGKey(2), cfg)
        B, Tp, new = 2, 5, 6
        prompt = jnp.asarray(rng.randint(0, cfg.vocab, (B, Tp)), jnp.int32)
        got = transformer.generate(params, prompt, cfg, max_new=new)
        assert got.shape == (B, Tp + new)
        ref = prompt
        for _ in range(new):
            logits = transformer.forward(params, ref, cfg)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            ref = jnp.concatenate([ref, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_sampling_reproducible_and_bounded(self, rng):
        cfg = self.CFG
        params = transformer.init_params(jax.random.PRNGKey(3), cfg)
        prompt = jnp.asarray(rng.randint(0, cfg.vocab, (1, 4)), jnp.int32)
        a = transformer.generate(params, prompt, cfg, max_new=5,
                                 temperature=1.0, key=jax.random.PRNGKey(9))
        b = transformer.generate(params, prompt, cfg, max_new=5,
                                 temperature=1.0, key=jax.random.PRNGKey(9))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(np.asarray(a).max()) < cfg.vocab
        with pytest.raises(ValueError, match="needs a key"):
            transformer.generate(params, prompt, cfg, max_new=2,
                                 temperature=0.5)
        with pytest.raises(ValueError, match="max_len"):
            transformer.generate(params, prompt, cfg, max_new=100)


class TestBeamSearch:
    CFG = transformer.TransformerConfig(
        vocab=20, d_model=16, n_layers=2, n_heads=2, d_ff=32, max_len=20,
        dtype=jnp.float32)

    def _score_of(self, params, cfg, seq, Tp):
        """Recompute a hypothesis's logprob with the plain forward."""
        logits = transformer.forward(params, seq[None, :-1], cfg)
        lp = jax.nn.log_softmax(logits, axis=-1)[0]
        tgt = seq[Tp:]
        pos = jnp.arange(Tp - 1, Tp - 1 + tgt.shape[0])
        return float(jnp.sum(lp[pos, tgt]))

    def test_scores_match_forward_recompute(self, rng):
        """Every returned hypothesis's reported score must equal the sum
        of stepwise log-probs under the plain forward — this pins both
        the lineage backtracking and the score accumulation."""
        cfg = self.CFG
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        B, Tp, new, K = 2, 4, 5, 3
        prompt = jnp.asarray(rng.randint(0, cfg.vocab, (B, Tp)), jnp.int32)
        seqs, scores = transformer.beam_search(params, prompt, cfg,
                                               max_new=new, beam_size=K)
        assert seqs.shape == (B, K, Tp + new)
        for b in range(B):
            # scores descending
            s = np.asarray(scores[b])
            assert (np.diff(s) <= 1e-6).all(), s
            for j in range(K):
                want = self._score_of(params, cfg, seqs[b, j], Tp)
                np.testing.assert_allclose(float(scores[b, j]), want,
                                           rtol=2e-4, atol=2e-3)

    def test_beam1_equals_greedy(self, rng):
        cfg = self.CFG
        params = transformer.init_params(jax.random.PRNGKey(1), cfg)
        prompt = jnp.asarray(rng.randint(0, cfg.vocab, (2, 3)), jnp.int32)
        beam, _ = transformer.beam_search(params, prompt, cfg, max_new=6,
                                          beam_size=1)
        greedy = transformer.generate(params, prompt, cfg, max_new=6)
        np.testing.assert_array_equal(np.asarray(beam[:, 0]),
                                      np.asarray(greedy))

    def test_beam_at_least_as_good_as_greedy(self, rng):
        cfg = self.CFG
        params = transformer.init_params(jax.random.PRNGKey(2), cfg)
        Tp, new = 3, 6
        prompt = jnp.asarray(rng.randint(0, cfg.vocab, (1, Tp)), jnp.int32)
        _, scores = transformer.beam_search(params, prompt, cfg,
                                            max_new=new, beam_size=4)
        greedy = transformer.generate(params, prompt, cfg, max_new=new)
        gs = self._score_of(params, cfg, greedy[0], Tp)
        assert float(scores[0, 0]) >= gs - 1e-4


class TestDropout:
    CFG = transformer.TransformerConfig(
        vocab=30, d_model=16, n_layers=2, n_heads=2, d_ff=32, max_len=16,
        dtype=jnp.float32, dropout=0.5)

    def test_no_key_is_deterministic_and_matches_rate0(self, rng):
        """Without a dropout_key the forward is the eval path — identical
        to a dropout=0 config (serving/eval can't silently drop)."""
        import dataclasses as dc
        params = transformer.init_params(jax.random.PRNGKey(0), self.CFG)
        toks = jnp.asarray(rng.randint(0, 30, (2, 8)), jnp.int32)
        a = transformer.forward(params, toks, self.CFG)
        b = transformer.forward(params, toks,
                                dc.replace(self.CFG, dropout=0.0))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_keys_randomize_and_reproduce(self, rng):
        params = transformer.init_params(jax.random.PRNGKey(0), self.CFG)
        toks = jnp.asarray(rng.randint(0, 30, (2, 8)), jnp.int32)
        k1, k2 = jax.random.PRNGKey(1), jax.random.PRNGKey(2)
        a1 = transformer.forward(params, toks, self.CFG, dropout_key=k1)
        a2 = transformer.forward(params, toks, self.CFG, dropout_key=k1)
        b = transformer.forward(params, toks, self.CFG, dropout_key=k2)
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
        assert np.abs(np.asarray(a1) - np.asarray(b)).max() > 0

    def test_grads_flow_with_dropout(self, rng):
        params = transformer.init_params(jax.random.PRNGKey(0), self.CFG)
        toks = jnp.asarray(rng.randint(0, 30, (2, 8)), jnp.int32)
        tgts = jnp.roll(toks, -1, axis=1)
        g = jax.grad(lambda p: transformer.lm_loss(
            p, toks, tgts, self.CFG,
            dropout_key=jax.random.PRNGKey(3)))(params)
        assert all(np.isfinite(np.asarray(x)).all()
                   for x in jax.tree.leaves(g))
        assert float(jnp.abs(g["blocks"]["qkv"]).max()) > 0


class TestTransformerCheckpoint:
    def test_roundtrip_preserves_generation(self, rng, tmp_path):
        """Functional-model serving flow: train a few steps, checkpoint
        the pytree, reload into fresh buffers, and greedy generation must
        be token-identical (the io/checkpoint pytree path + KV-cache
        decode integration)."""
        from paddle_tpu import optimizer as popt
        from paddle_tpu.io import checkpoint as ckpt

        cfg = transformer.TransformerConfig(
            vocab=40, d_model=16, n_layers=2, n_heads=2, d_ff=32,
            max_len=24, dtype=jnp.float32)
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        adam = popt.Adam(learning_rate=1e-2)
        ost = adam.tree_init_state(params)
        toks = jnp.asarray(rng.randint(0, 40, (4, 12)), jnp.int32)
        tgts = jnp.roll(toks, -1, axis=1)
        for i in range(3):
            _, g = jax.value_and_grad(transformer.lm_loss)(
                params, toks, tgts, cfg)
            params, ost = adam.tree_update(jnp.asarray(i, jnp.int32), g,
                                           params, ost)
        path = ckpt.save_checkpoint(str(tmp_path), 3, params,
                                    opt_state=ost)
        prompt = toks[:1, :5]
        want = transformer.generate(params, prompt, cfg, max_new=6)

        fresh = transformer.init_params(jax.random.PRNGKey(99), cfg)
        fost = adam.tree_init_state(fresh)
        step, loaded, lost, _ = ckpt.load_checkpoint(
            ckpt.latest_checkpoint(str(tmp_path)), fresh, opt_state=fost)
        assert step == 3
        got = transformer.generate(loaded, prompt, cfg, max_new=6)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # optimizer state restored too (training can resume)
        la, lb = jax.tree.leaves(ost), jax.tree.leaves(lost)
        assert any(float(jnp.abs(a).max()) > 0 for a in la)
        for a, b in zip(la, lb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))


class TestRoPE:
    CFG = transformer.TransformerConfig(
        vocab=30, d_model=16, n_layers=2, n_heads=2, d_ff=32, max_len=24,
        dtype=jnp.float32, use_rope=True)

    def test_decode_matches_forward(self, rng):
        """The KV cache must hold ROTATED keys so incremental decode
        reproduces the full forward under RoPE too."""
        cfg = self.CFG
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        B, T = 2, 9
        toks = jnp.asarray(rng.randint(0, 30, (B, T)), jnp.int32)
        full = transformer.forward(params, toks, cfg)
        cache = transformer.init_cache(cfg, B, T)
        for t in range(T):
            logits, cache = transformer.decode_step(
                params, cache, toks[:, t], jnp.asarray(t, jnp.int32), cfg)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full[:, t]),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"position {t}")

    def test_relative_shift_invariance(self, rng):
        """The defining RoPE property, checked directly: the q·k score
        between two positions depends only on their OFFSET —
        dot(rope(q, p+s), rope(k, p'+s)) == dot(rope(q, p), rope(k, p'))
        for any shift s. (The causal prefix property alone would pass
        even with a broken rotation.)"""
        Dh = 8
        q = jnp.asarray(rng.randn(1, 1, 1, Dh).astype(np.float32))
        k = jnp.asarray(rng.randn(1, 1, 1, Dh).astype(np.float32))

        def score(pq, pk):
            tq = transformer._rope_tables(
                jnp.asarray([pq], jnp.int32), Dh, 10000.0)
            tk = transformer._rope_tables(
                jnp.asarray([pk], jnp.int32), Dh, 10000.0)
            return float(jnp.sum(transformer._rope(q, tq) *
                                 transformer._rope(k, tk)))

        base = score(3, 1)
        for shift in (1, 5, 11):
            np.testing.assert_allclose(score(3 + shift, 1 + shift), base,
                                       rtol=1e-5)
        # and a DIFFERENT offset gives a different score
        assert abs(score(4, 1) - base) > 1e-4

    def test_odd_head_dim_rejected(self):
        with pytest.raises(ValueError, match="even head_dim"):
            transformer._rope_tables(jnp.asarray([0], jnp.int32), 9,
                                     10000.0)

    def test_generate_and_beam_run(self, rng):
        """Greedy decode equals beam_size=1 EXACTLY under RoPE — both
        paths break logit ties stably toward the lower token id (argmax
        and top_k share that contract), so this holds even on a
        random-init toy model with near-tied logits. Against a wider
        beam only the SCORE ordering is an invariant: beam-2 may
        legitimately out-score the greedy path (that was the old
        flaky assert — greedy == beam-2's best is not a theorem)."""
        cfg = self.CFG
        params = transformer.init_params(jax.random.PRNGKey(2), cfg)
        prompt = jnp.asarray(rng.randint(0, 30, (1, 4)), jnp.int32)
        g = transformer.generate(params, prompt, cfg, max_new=5)
        b1, _ = transformer.beam_search(params, prompt, cfg, max_new=5,
                                        beam_size=1)
        np.testing.assert_array_equal(np.asarray(g),
                                      np.asarray(b1[:, 0]))
        b2, s2 = transformer.beam_search(params, prompt, cfg, max_new=5,
                                         beam_size=2)
        assert b2.shape == (1, 2, 9) and s2.shape == (1, 2)
        # beam-2's best hypothesis scores at least the greedy path
        logits = transformer.forward(params, g[:, :-1], cfg)
        lp = jax.nn.log_softmax(logits, axis=-1)[0]
        pos = jnp.arange(3, 8)
        greedy_score = float(jnp.sum(lp[pos, g[0, 4:]]))
        assert float(s2[0, 0]) >= greedy_score - 1e-4

    def test_ring_flash_matches_full_under_rope(self, rng):
        """RoPE applies before the attention engine, so ring+flash CP
        must agree with single-device full attention bit-for-bit-ish."""
        import dataclasses as dc
        cfg = dc.replace(self.CFG, use_ring_attention=True,
                         use_flash_attention=True, max_len=32)
        mesh = place.make_mesh((1, 2, 1), (place.AXIS_DATA, place.AXIS_SEQ,
                                           place.AXIS_MODEL))
        params = transformer.init_params(jax.random.PRNGKey(3), cfg)
        toks = jnp.asarray(rng.randint(0, 30, (2, 32)), jnp.int32)
        ref_cfg = dc.replace(cfg, use_ring_attention=False,
                             use_flash_attention=False)
        want = transformer.forward(params, toks, ref_cfg)
        got = transformer.forward(params, toks, cfg, mesh=mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)


class TestGQA:
    CFG = transformer.TransformerConfig(
        vocab=30, d_model=16, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=32, max_len=20, dtype=jnp.float32, use_rope=True)

    def test_decode_matches_forward(self, rng):
        """Grouped-query attention: the Hkv-head cache must reproduce the
        full forward (which repeats kv heads for the engines)."""
        cfg = self.CFG
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        B, T = 2, 8
        toks = jnp.asarray(rng.randint(0, 30, (B, T)), jnp.int32)
        full = transformer.forward(params, toks, cfg)
        cache = transformer.init_cache(cfg, B, T)
        assert cache["k"].shape == (2, B, T, 2, 4)   # Hkv=2 not H=4
        for t in range(T):
            logits, cache = transformer.decode_step(
                params, cache, toks[:, t], jnp.asarray(t, jnp.int32), cfg)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full[:, t]),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"position {t}")

    def test_cache_half_the_size_and_generate_runs(self, rng):
        import dataclasses as dc
        cfg = self.CFG
        mha = dc.replace(cfg, n_kv_heads=0)
        gq = transformer.init_cache(cfg, 1, 16)
        mh = transformer.init_cache(mha, 1, 16)
        assert gq["k"].size * 2 == mh["k"].size
        params = transformer.init_params(jax.random.PRNGKey(1), cfg)
        prompt = jnp.asarray(rng.randint(0, 30, (1, 4)), jnp.int32)
        out = transformer.generate(params, prompt, cfg, max_new=5)
        assert out.shape == (1, 9)

    def test_invalid_ratio_rejected(self):
        cfg = transformer.TransformerConfig(vocab=10, d_model=16,
                                            n_heads=4, n_kv_heads=3)
        with pytest.raises(ValueError, match="multiple"):
            transformer.init_params(jax.random.PRNGKey(0), cfg)

    def test_lm_learns_with_gqa(self, rng):
        cfg = self.CFG
        params = transformer.init_params(jax.random.PRNGKey(2), cfg)
        toks = jnp.asarray((np.arange(16)[None, :] +
                            rng.randint(0, 30, (4, 1))) % 30, jnp.int32)
        tgts = (toks + 1) % 30
        step = jax.jit(jax.value_and_grad(
            lambda p: transformer.lm_loss(p, toks, tgts, cfg)))
        hist = []
        for _ in range(25):
            l, g = step(params)
            params = jax.tree.map(lambda p, gr: p - 0.1 * gr, params, g)
            hist.append(float(l))
        assert hist[-1] < hist[0] * 0.6, (hist[0], hist[-1])


class TestAllToAllAttention:
    """Ulysses-style CP: all-to-all head-scatter instead of the K/V
    ring — must match full attention exactly, GQA included."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, rng, causal):
        mesh = place.make_mesh((2, 4), (place.AXIS_DATA, place.AXIS_SEQ))
        B, T, H, D = 4, 16, 4, 8
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        lens = jnp.asarray(np.array([16, 9, 12, 5], np.int32))
        got = ring.alltoall_attention_spmd(q, k, v, mesh, causal=causal,
                                           lengths=lens)
        want = ring.full_attention(q, k, v, causal=causal, lengths=lens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("use_flash", [False, True])
    def test_grads_and_gqa(self, rng, use_flash):
        mesh = place.make_mesh((1, 4), (place.AXIS_DATA, place.AXIS_SEQ))
        B, T, H, Hkv, D = 2, 16, 8, 4, 4
        q = rng.randn(B, T, H, D).astype(np.float32)
        k = rng.randn(B, T, Hkv, D).astype(np.float32)
        v = rng.randn(B, T, Hkv, D).astype(np.float32)

        def loss_a2a(q_, k_, v_):
            return jnp.sum(ring.alltoall_attention_spmd(
                jnp.asarray(q_), jnp.asarray(k_), jnp.asarray(v_), mesh,
                causal=True, use_flash=use_flash, interpret=True) ** 2)

        def loss_full(q_, k_, v_):
            return jnp.sum(ring.full_attention(
                jnp.asarray(q_), jnp.asarray(k_), jnp.asarray(v_),
                causal=True) ** 2)

        got = ring.alltoall_attention_spmd(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh,
            causal=True, use_flash=use_flash, interpret=True)
        want = ring.full_attention(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v), causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        g_a = jax.grad(loss_a2a, argnums=(0, 1, 2))(q, k, v)
        g_f = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g_a, g_f):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5,
                                       err_msg=f"d{name}")

    def test_rejects_nondividing_heads(self, rng):
        mesh = place.make_mesh((1, 4), (place.AXIS_DATA, place.AXIS_SEQ))
        x = jnp.zeros((2, 16, 6, 4), jnp.float32)   # 6 heads, P=4
        with pytest.raises(ValueError, match="must divide"):
            ring.alltoall_attention_spmd(x, x, x, mesh, causal=True)

    def test_transformer_cp_mode_alltoall(self, rng):
        import dataclasses as dc
        mesh = place.make_mesh((2, 4), (place.AXIS_DATA, place.AXIS_SEQ))
        cfg = dc.replace(CFG, use_ring_attention=True,
                         cp_mode="alltoall", max_len=32)
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(rng.randint(0, 50, (2, 32)).astype(np.int32))
        got = transformer.forward(params, toks, cfg, mesh=mesh)
        ref_cfg = dc.replace(cfg, use_ring_attention=False)
        want = transformer.forward(params, toks, ref_cfg)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-3, atol=1e-4)

    def test_head_axis_tp_composes(self, rng):
        """dp x sp x tp mesh: heads shard over model, scatter over seq —
        still exact."""
        mesh = place.make_mesh(
            (2, 2, 2), (place.AXIS_DATA, place.AXIS_SEQ, place.AXIS_MODEL))
        B, T, H, D = 2, 16, 8, 4
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        got = jax.jit(lambda a, b, c: ring.alltoall_attention_spmd(
            a, b, c, mesh, causal=True))(q, k, v)
        want = ring.full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_cp_mode_validated(self):
        import dataclasses as dc
        import pytest as pt
        with pt.raises(ValueError, match="cp_mode"):
            dc.replace(CFG, cp_mode="ulysses")


class TestLayerRemat:
    """cfg.remat: layer-granular recompute with a (quantized) stash of
    each block's input (ops/q8.q8_remat) — the long-context capacity
    lever. Forward must be EXACT (the stash is backward-only); grads
    match to stash tolerance; the fwd+bwd temp footprint shrinks."""

    def _setup(self, max_len=64, T=32):
        cfg = transformer.TransformerConfig(
            vocab=64, d_model=32, n_heads=4, n_layers=3, d_ff=64,
            max_len=max_len, dtype=jnp.float32)
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        toks = jnp.asarray(rng.randint(0, 64, (2, T)).astype(np.int32))
        tgt = jnp.asarray(rng.randint(0, 64, (2, T)).astype(np.int32))
        return cfg, params, toks, tgt

    @pytest.mark.parametrize("mode,tol", [("bf16", 0.02), ("q8", 0.08)])
    def test_forward_exact_grads_close(self, mode, tol):
        cfg, params, toks, tgt = self._setup()
        ref_l, ref_g = jax.value_and_grad(transformer.lm_loss)(
            params, toks, tgt, cfg)
        rcfg = dataclasses.replace(cfg, remat=mode)
        loss, g = jax.value_and_grad(transformer.lm_loss)(
            params, toks, tgt, rcfg)
        assert float(loss) == float(ref_l), "remat changed the forward"
        worst = max(jax.tree_util.tree_leaves(jax.tree_util.tree_map(
            lambda a, b: float(jnp.abs(a - b).max()
                               / (jnp.abs(b).max() + 1e-9)), g, ref_g)))
        assert worst < tol, f"remat={mode} worst grad rel err {worst}"

    def test_temp_memory_shrinks(self):
        cfg, params, _, _ = self._setup(max_len=512, T=512)
        rng = np.random.RandomState(1)
        toks = jnp.asarray(rng.randint(0, 64, (2, 512)).astype(np.int32))

        def temp(mode):
            c = dataclasses.replace(cfg, remat=mode)
            f = jax.jit(lambda p, t, g: jax.value_and_grad(
                transformer.lm_loss)(p, t, g, c))
            return f.lower(params, toks,
                           toks).compile().memory_analysis().temp_size_in_bytes

        none, q8r = temp("none"), temp("q8")
        assert q8r < 0.5 * none, (none, q8r)

    def test_composes_with_ring_flash(self):
        """remat=q8 under ring-CP + flash on the seq mesh trains."""
        mesh = place.make_mesh((1, 8, 1), (place.AXIS_DATA, place.AXIS_SEQ,
                                           place.AXIS_MODEL))
        cfg = transformer.TransformerConfig(
            vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_len=64, dtype=jnp.float32, use_ring_attention=True,
            use_flash_attention=True, remat="q8")
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        sharded = jax.tree_util.tree_map(
            jax.device_put, params, transformer.param_shardings(cfg, mesh))
        rng = np.random.RandomState(0)
        toks = jnp.asarray(rng.randint(0, 64, (1, 64)).astype(np.int32))

        @jax.jit
        def step(p, tk):
            loss, g = jax.value_and_grad(transformer.lm_loss)(
                p, tk, tk, cfg, mesh=mesh)
            return loss, jax.tree_util.tree_map(
                lambda w, gr: w - 0.1 * gr, p, g)

        l1, p2 = step(sharded, toks)
        l2, _ = step(p2, toks)
        assert float(l2) < float(l1)


class TestWireInt8:
    """int8 wire codecs for the distributed sends (ops/q8
    make_ppermute_q8): ring-CP K/V rotations and pipeline inter-stage
    activations travel as int8 + per-shard scales, both directions."""

    def test_ring_attention_wire_int8_close(self, rng):
        mesh = place.make_mesh((1, 8), (place.AXIS_DATA, place.AXIS_SEQ))
        B, T, H, D = 2, 32, 2, 8
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32)) * 0.5
        k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32)) * 0.5
        v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        ref = ring.ring_attention_spmd(q, k, v, mesh, causal=True)
        got = ring.ring_attention_spmd(q, k, v, mesh, causal=True,
                                       wire_int8=True)
        rel = float(jnp.abs(got - ref).max() / (jnp.abs(ref).max() + 1e-9))
        assert rel < 0.05, f"wire-int8 ring rel err {rel}"

    def test_ring_wire_int8_grads_flow(self, rng):
        mesh = place.make_mesh((1, 8), (place.AXIS_DATA, place.AXIS_SEQ))
        B, T, H, D = 1, 16, 2, 4
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))

        def loss(q_, k_, v_):
            return jnp.sum(ring.ring_attention_spmd(
                q_, k_, v_, mesh, causal=True, wire_int8=True) ** 2)

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, q, q)
        for g in (gq, gk, gv):
            assert jnp.isfinite(g).all()
            assert float(jnp.abs(g).max()) > 0

    def test_flash_ring_wire_int8_close(self, rng):
        """The flash engine's K/V hops (fwd and bwd re-walk) use the
        codec too; grads stay close to the full-precision flash ring."""
        mesh = place.make_mesh((1, 8), (place.AXIS_DATA, place.AXIS_SEQ))
        B, T, H, D = 1, 64, 2, 8
        q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32)) * 0.5

        def loss(wire):
            def f(q_, k_, v_):
                return jnp.sum(ring.ring_attention_spmd(
                    q_, k_, v_, mesh, causal=True, use_flash=True,
                    wire_int8=wire) ** 2)
            return f

        ref = ring.ring_attention_spmd(q, q, q, mesh, causal=True,
                                       use_flash=True)
        got = ring.ring_attention_spmd(q, q, q, mesh, causal=True,
                                       use_flash=True, wire_int8=True)
        rel = float(jnp.abs(got - ref).max() / (jnp.abs(ref).max() + 1e-9))
        assert rel < 0.05, f"flash wire-int8 fwd rel err {rel}"
        g_ref = jax.grad(loss(False), argnums=(0, 1, 2))(q, q, q)
        g_got = jax.grad(loss(True), argnums=(0, 1, 2))(q, q, q)
        for name, a, b in zip("dq dk dv".split(), g_got, g_ref):
            r = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
            assert r < 0.08, f"flash wire-int8 {name} rel err {r}"

    def test_pipeline_wire_int8_trains(self, rng):
        from paddle_tpu.parallel import pipeline
        mesh = place.make_mesh((4,), (place.AXIS_STAGE,))
        S, D, B, M = 4, 8, 16, 4
        params = {"w": jnp.asarray(rng.randn(S, D, D).astype(np.float32)
                                   * 0.3),
                  "b": jnp.zeros((S, D), jnp.float32)}
        x = jnp.asarray(rng.randn(B, D).astype(np.float32))
        y = jnp.asarray(rng.randn(B, D).astype(np.float32) * 0.1)

        def stage_fn(p, h):
            return jnp.tanh(h @ p["w"] + p["b"])

        ref = pipeline.pipeline_apply(params, x, stage_fn, mesh, M)
        got = pipeline.pipeline_apply(params, x, stage_fn, mesh, M,
                                      wire_int8=True)
        rel = float(jnp.abs(got - ref).max() / (jnp.abs(ref).max() + 1e-9))
        assert rel < 0.05, f"wire-int8 pipeline rel err {rel}"

        @jax.jit
        def train_step(p):
            def loss(p_):
                out = pipeline.pipeline_apply(p_, x, stage_fn, mesh, M,
                                              wire_int8=True)
                return jnp.mean((out - y) ** 2)
            l, g = jax.value_and_grad(loss)(p)
            return l, jax.tree_util.tree_map(lambda w, gr: w - 0.2 * gr,
                                             p, g)

        l1, p2 = train_step(params)
        l2, _ = train_step(p2)
        assert float(l2) < float(l1)

    def test_int8_actually_crosses_the_wire(self):
        """HLO-level guard against silent no-op codecs (the failure mode
        that killed the MoE attempt): the compiled programs must contain
        collective-permutes on s8 operands."""
        import re
        mesh = place.make_mesh((1, 8), (place.AXIS_DATA, place.AXIS_SEQ))
        q = jnp.zeros((1, 32, 2, 8), jnp.float32)
        f = jax.jit(lambda q: ring.ring_attention_spmd(
            q, q, q, mesh, causal=True, wire_int8=True))
        txt = f.lower(q).compile().as_text()
        cp_lines = [l for l in txt.splitlines()
                    if "collective-permute" in l]
        assert any("s8[" in l for l in cp_lines), \
            "ring wire_int8: no int8 collective-permute in compiled HLO"

        from paddle_tpu.parallel import pipeline
        m2 = place.make_mesh((4,), (place.AXIS_STAGE,))
        params = {"w": jnp.zeros((4, 8, 8), jnp.float32),
                  "b": jnp.zeros((4, 8), jnp.float32)}
        x = jnp.zeros((16, 8), jnp.float32)
        g = jax.jit(lambda p, x: pipeline.pipeline_apply(
            p, x, lambda pp, h: jnp.tanh(h @ pp["w"] + pp["b"]),
            m2, 4, wire_int8=True))
        txt2 = g.lower(params, x).compile().as_text()
        cp2 = [l for l in txt2.splitlines() if "collective-permute" in l]
        assert any("s8[" in l for l in cp2), \
            "pipeline wire_int8: no int8 collective-permute in HLO"
