"""Detection suite (priorbox/multibox_loss/detection_output/roi_pool) and
chunk/CTC-error/mAP evaluators vs numpy references.

Reference analog: gserver/tests/test_PriorBox.cpp, test_DetectionOutput.cpp,
test_Evaluator.cpp, ChunkEvaluator/CTCErrorEvaluator/DetectionMAPEvaluator.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import evaluator, layer
from paddle_tpu.ops import detection as ops_det
from paddle_tpu.topology import Topology, Value
from paddle_tpu.utils.rng import KeySource


def np_iou(a, b):
    x1 = max(a[0], b[0]); y1 = max(a[1], b[1])
    x2 = min(a[2], b[2]); y2 = min(a[3], b[3])
    inter = max(0, x2 - x1) * max(0, y2 - y1)
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return inter / ua if ua > 0 else 0.0


class TestDetectionOps:
    def test_iou_matrix(self, rng):
        a = np.sort(rng.rand(5, 2, 2), axis=1).reshape(5, 4)[:, [0, 2, 1, 3]]
        b = np.sort(rng.rand(4, 2, 2), axis=1).reshape(4, 4)[:, [0, 2, 1, 3]]
        got = np.asarray(ops_det.iou_matrix(jnp.asarray(a, jnp.float32),
                                            jnp.asarray(b, jnp.float32)))
        for i in range(5):
            for j in range(4):
                np.testing.assert_allclose(got[i, j], np_iou(a[i], b[j]),
                                           rtol=1e-4, atol=1e-5)

    def test_encode_decode_roundtrip(self, rng):
        priors = np.array([[0.1, 0.1, 0.5, 0.5], [0.4, 0.4, 0.9, 0.8]],
                          np.float32)
        gt = np.array([[0.15, 0.2, 0.45, 0.55], [0.35, 0.42, 0.8, 0.85]],
                      np.float32)
        enc = ops_det.encode_boxes(jnp.asarray(gt), jnp.asarray(priors))
        dec = ops_det.decode_boxes(enc, jnp.asarray(priors))
        np.testing.assert_allclose(np.asarray(dec), gt, rtol=1e-4, atol=1e-5)

    def test_prior_boxes_properties(self):
        pb = np.asarray(ops_det.prior_boxes(2, 3, 100, 100, min_size=30,
                                            max_size=60,
                                            aspect_ratios=(2.0,)))
        # 2x3 cells x (1 min + 1 sqrt + 2 ar) = 24 boxes
        assert pb.shape == (24, 4)
        assert (pb >= 0).all() and (pb <= 1).all()
        # first box is the min box at cell (0,0): center ~ (1/6, 1/4)
        np.testing.assert_allclose((pb[0, 0] + pb[0, 2]) / 2, 1 / 6,
                                   atol=1e-6)

    def test_nms_suppresses_overlaps(self):
        boxes = np.array([
            [0.1, 0.1, 0.4, 0.4],
            [0.12, 0.12, 0.42, 0.42],   # overlaps 0
            [0.6, 0.6, 0.9, 0.9],
            [0.61, 0.61, 0.91, 0.91],   # overlaps 2
        ], np.float32)
        scores = np.array([0.9, 0.8, 0.95, 0.5], np.float32)
        sel, sc = ops_det.nms(jnp.asarray(boxes), jnp.asarray(scores),
                              max_out=4, iou_threshold=0.5)
        sel = [int(i) for i in np.asarray(sel) if i >= 0]
        assert sel == [2, 0]

    def test_match_priors_forces_best(self):
        priors = jnp.asarray(np.array([
            [0.0, 0.0, 0.3, 0.3],
            [0.5, 0.5, 0.9, 0.9],
            [0.05, 0.05, 0.35, 0.35],
        ], np.float32))
        gt = jnp.asarray(np.array([[0.0, 0.0, 0.31, 0.31]], np.float32))
        match, miou = ops_det.match_priors(priors, gt,
                                           jnp.asarray([True]), 0.5)
        match = np.asarray(match)
        assert match[0] == 0          # high IoU
        assert match[1] == -1         # no overlap
        assert float(miou[0]) > 0.8

    def test_roi_pool(self, rng):
        feat = rng.randn(6, 6, 2).astype(np.float32)
        rois = np.array([[0, 0, 3, 3], [2, 2, 6, 6]], np.float32)
        out = np.asarray(ops_det.roi_pool(jnp.asarray(feat),
                                          jnp.asarray(rois), 2, 2))
        assert out.shape == (2, 2, 2, 2)
        # top-left cell of roi 0 = max over feat[0:2, 0:2]
        np.testing.assert_allclose(out[0, 0, 0], feat[0:2, 0:2].max((0, 1)),
                                   rtol=1e-6)


class TestDetectionLayers:
    def _build(self, num_classes=3, npri=None):
        img = layer.data("img", paddle.data_type.dense_vector(2 * 4 * 4))
        img._out_channels = 2
        img._img_shape = (4, 4)
        pb = layer.priorbox(img, image_size=100, min_size=30,
                            aspect_ratio=(), name="pb")
        P = pb.num_priors
        loc = layer.fc(img, P * 4, act="linear", name="loc")
        conf = layer.fc(img, P * num_classes, act="linear", name="conf")
        return img, pb, loc, conf, P

    def test_multibox_loss_trains(self, rng):
        C = 3
        img, pb, loc, conf, P = self._build(C)
        gt = layer.data("gt", paddle.data_type.dense_vector(5))
        cost = layer.multibox_loss(loc, conf, pb, gt, num_classes=C,
                                   name="mbl")
        topo = Topology(cost)
        params = paddle.parameters.create(cost, KeySource(0))
        fwd = topo.compile()
        B, G = 4, 2
        x = jnp.asarray(rng.randn(B, 32).astype(np.float32))
        gtb = np.zeros((B, G, 5), np.float32)
        for b in range(B):
            gtb[b, 0] = [1, 0.1, 0.1, 0.45, 0.45]
            gtb[b, 1] = [2, 0.55, 0.55, 0.95, 0.95]
        glens = jnp.asarray(np.full(B, G, np.int32))
        feeds = {"img": Value(x),
                 "gt": Value(jnp.asarray(gtb), lengths=glens)}

        def loss(p):
            o, _ = fwd(p, params.state, feeds)
            return jnp.mean(o["mbl"].array)

        step = jax.jit(jax.value_and_grad(loss))
        vals, hist = params.values, []
        for _ in range(40):
            l, g = step(vals)
            vals = jax.tree_util.tree_map(lambda w, gr: w - 0.01 * gr,
                                          vals, g)
            hist.append(float(l))
        assert np.isfinite(hist).all()
        assert hist[-1] < hist[0] * 0.8, (hist[0], hist[-1])
        self._trained = (vals, params)

    def test_detection_output_shape_and_order(self, rng):
        C = 3
        img, pb, loc, conf, P = self._build(C)
        det = layer.detection_output(loc, conf, pb, num_classes=C,
                                     keep_top_k=10, name="det")
        topo = Topology(det)
        params = paddle.parameters.create(det, KeySource(0))
        fwd = jax.jit(lambda p, s, f: topo.compile()(p, s, f)[0])
        x = jnp.asarray(rng.randn(2, 32).astype(np.float32))
        o = fwd(params.values, params.state, {"img": Value(x)})
        d = np.asarray(o["det"].array)
        assert d.shape == (2, 10, 6)
        valid = d[0][d[0][:, 0] >= 0]
        assert np.all(np.diff(valid[:, 1]) <= 1e-6)   # score-sorted


class TestChunkEvaluator:
    def _run(self, pred_tags, lab_tags, lens, num_types=2, scheme="IOB"):
        T = pred_tags.shape[1]
        ntag = pred_tags.max() + 1
        p = layer.data("p", paddle.data_type.integer_value_sequence(10))
        l = layer.data("l", paddle.data_type.integer_value_sequence(10))
        ev = evaluator.chunk(p, l, num_chunk_types=num_types,
                             chunk_scheme=scheme, name="ch")
        topo = Topology(ev)
        params = paddle.parameters.create(ev, KeySource(0))
        fwd = topo.compile()
        o, _ = fwd(params.values, params.state, {
            "p": Value(jnp.asarray(pred_tags), jnp.asarray(lens)),
            "l": Value(jnp.asarray(lab_tags), jnp.asarray(lens))})
        acc = evaluator.MetricAccumulator("ch", ev.metric_finalize, 3)
        acc.add(o["ch"].array)
        return np.asarray(o["ch"].array), acc.value()

    def test_iob_exact(self):
        # 2 chunk types, IOB: B0=0 I0=1 B1=2 I1=3 O=4
        lab = np.array([[0, 1, 4, 2, 3, 4]], np.int32)       # 2 gold chunks
        pred = np.array([[0, 1, 4, 2, 1, 4]], np.int32)      # 2nd broken
        vec, m = self._run(pred, lab, np.array([6], np.int32))
        assert list(vec) == [1.0, 3.0, 2.0]   # pred has B0,B1,B0(I-as-start)
        assert abs(m["recall"] - 0.5) < 1e-9

    def test_iob_perfect(self):
        lab = np.array([[0, 1, 1, 4, 2, 4], [4, 0, 4, 4, 4, 4]], np.int32)
        vec, m = self._run(lab, lab, np.array([6, 3], np.int32))
        assert m["f1"] == pytest.approx(1.0)
        assert list(vec) == [3.0, 3.0, 3.0]

    def test_iobes_chunk_to_sequence_end(self):
        # IOBES: B=0 I=1 E=2 S=3 per type; 1 type => O=4
        # chunk [B, I] running to sequence end must count as one chunk
        lab = np.array([[0, 1]], np.int32)
        vec, m = self._run(lab, lab, np.array([2], np.int32),
                           num_types=1, scheme="IOBES")
        assert list(vec) == [1.0, 1.0, 1.0]
        assert m["f1"] == pytest.approx(1.0)

    def test_iobes_singles_and_pairs(self):
        # S(3), then B-E pair, then O
        lab = np.array([[3, 0, 2, 4]], np.int32)
        vec, m = self._run(lab, lab, np.array([4], np.int32),
                           num_types=1, scheme="IOBES")
        assert list(vec) == [2.0, 2.0, 2.0]

    def test_padding_ignored(self):
        lab = np.array([[0, 1, 0, 0, 0, 0]], np.int32)
        # length 2: only one chunk [0,1]; padded zeros must not count
        vec, _ = self._run(lab, lab, np.array([2], np.int32))
        assert list(vec) == [1.0, 1.0, 1.0]


class TestCTCErrorEvaluator:
    def test_edit_distance(self):
        V = 4   # classes incl blank(last)
        T, L = 5, 4
        p = layer.data("p", paddle.data_type.dense_vector_sequence(V))
        l = layer.data("l", paddle.data_type.integer_value_sequence(V))
        ev = evaluator.ctc_error(p, l, name="cer")
        topo = Topology(ev)
        params = paddle.parameters.create(ev, KeySource(0))
        fwd = topo.compile()
        # frames argmax: [1,1,3,2,2] -> collapse(blank=3) -> [1,2]
        logits = np.full((1, T, V), -5.0, np.float32)
        for t, c in enumerate([1, 1, 3, 2, 2]):
            logits[1 - 1, t, c] = 5.0
        lab = np.zeros((1, L), np.int32)
        lab[0, :3] = [1, 0, 2]            # gold [1,0,2]: edit dist 1
        o, _ = fwd(params.values, params.state, {
            "p": Value(jnp.asarray(logits), jnp.asarray([T])),
            "l": Value(jnp.asarray(lab), jnp.asarray([3]))})
        vec = np.asarray(o["cer"].array)
        assert vec[0] == pytest.approx(1.0)    # one insertion missing
        assert vec[1] == 3.0


class TestDetectionMAP:
    def test_perfect_detections_map_1(self):
        C, K, G = 3, 4, 2
        det_l = layer.data("d", paddle.data_type.dense_vector(6))
        gt_l = layer.data("g", paddle.data_type.dense_vector(5))
        ev = evaluator.detection_map(det_l, gt_l, num_classes=C, name="map")
        topo = Topology(ev)
        params = paddle.parameters.create(ev, KeySource(0))
        fwd = topo.compile()
        gt = np.array([[[1, 0.1, 0.1, 0.4, 0.4],
                        [2, 0.5, 0.5, 0.9, 0.9]]], np.float32)
        det = np.full((1, K, 6), -1, np.float32)
        det[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]
        det[0, 1] = [2, 0.8, 0.5, 0.5, 0.9, 0.9]
        o, _ = fwd(params.values, params.state, {
            "d": Value(jnp.asarray(det)),
            "g": Value(jnp.asarray(gt), lengths=jnp.asarray([G]))})
        acc = evaluator.MetricAccumulator("map", ev.metric_finalize,
                                          ev.metric_width)
        acc.add(o["map"].array)
        assert acc.value() == pytest.approx(1.0, abs=1e-6)

    def test_false_positives_lower_map(self):
        C, K = 3, 4
        det_l = layer.data("d", paddle.data_type.dense_vector(6))
        gt_l = layer.data("g", paddle.data_type.dense_vector(5))
        ev = evaluator.detection_map(det_l, gt_l, num_classes=C, name="map2")
        topo = Topology(ev)
        params = paddle.parameters.create(ev, KeySource(0))
        fwd = topo.compile()
        gt = np.array([[[1, 0.1, 0.1, 0.4, 0.4]]], np.float32)
        det = np.full((1, K, 6), -1, np.float32)
        det[0, 0] = [1, 0.9, 0.6, 0.6, 0.9, 0.9]   # FP (wrong place)
        det[0, 1] = [1, 0.8, 0.1, 0.1, 0.4, 0.4]   # TP at lower score
        o, _ = fwd(params.values, params.state, {
            "d": Value(jnp.asarray(det)),
            "g": Value(jnp.asarray(gt), lengths=jnp.asarray([1]))})
        acc = evaluator.MetricAccumulator("m", ev.metric_finalize,
                                          ev.metric_width)
        acc.add(o["map2"].array)
        v = acc.value()
        assert 0.0 < v < 1.0
