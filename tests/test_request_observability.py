"""Request-lifecycle observability (PR 7 tentpole): the sliding-window
quantile estimator, the bounded per-request attribution ring, request
lifecycle tracing joined in the Chrome-trace export, SLO burn-rate
degradation on /healthz, the /requests endpoint, trainer step
bottleneck attribution, and the perf-regression sentinel."""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer, observe
from paddle_tpu.observe import bottleneck
from paddle_tpu.observe import requests as oreq
from paddle_tpu.observe.window import SloConfig, WindowedQuantiles


@pytest.fixture(autouse=True)
def _isolate_observe():
    observe.reset()
    yield
    observe.reset()


class TestWindowedQuantiles:
    def test_exact_quantiles_on_known_sequence(self):
        w = WindowedQuantiles(window_s=100.0)
        for i, v in enumerate([5.0, 1.0, 9.0, 3.0, 7.0]):
            w.observe(v, t=float(i))
        # nearest-rank over sorted [1,3,5,7,9] (the serving_bench _pct
        # convention): q*(n-1) rounded
        assert w.quantile(0.0, now=4.0) == 1.0
        assert w.quantile(0.5, now=4.0) == 5.0
        assert w.quantile(1.0, now=4.0) == 9.0
        assert w.quantile(0.75, now=4.0) == 7.0
        qs = w.quantiles((0.0, 0.5, 1.0), now=4.0)
        assert (qs[0.0], qs[0.5], qs[1.0]) == (1.0, 5.0, 9.0)

    def test_window_expiry_drops_old_samples(self):
        w = WindowedQuantiles(window_s=10.0)
        w.observe(100.0, t=0.0)
        w.observe(1.0, t=9.0)
        assert w.quantile(1.0, now=9.0) == 100.0     # both live
        # t=0 sample ages out at now > 10
        assert w.quantile(1.0, now=10.5) == 1.0
        assert w.count(now=10.5) == 1
        assert w.quantile(0.5, now=25.0) == 0.0      # empty window
        assert w.count(now=25.0) == 0

    def test_max_samples_bound(self):
        w = WindowedQuantiles(window_s=1e9, max_samples=8)
        for i in range(100):
            w.observe(float(i), t=float(i))
        assert w.count(now=99.0) == 8
        # only the newest 8 (92..99) survive
        assert w.quantile(0.0, now=99.0) == 92.0

    def test_agreement_with_cumulative_histogram_stationary(self):
        """On a stationary stream the windowed estimator and the
        cumulative histogram answer the same question: the windowed
        (exact) quantile must land within the histogram's answer's
        bucket (bucket-upper-bound semantics)."""
        buckets = (0.01, 0.02, 0.05, 0.1, 0.2, 0.5, 1.0)
        h = observe.Histogram("agree_h", buckets=buckets)
        w = WindowedQuantiles(window_s=1e9)
        rng = np.random.RandomState(0)
        vals = rng.gamma(2.0, 0.03, size=2000)       # latency-shaped
        for i, v in enumerate(vals):
            h.observe(float(v))
            w.observe(float(v), t=float(i))
        for q in (0.5, 0.95, 0.99):
            hist_q = h.quantile(q)
            exact_q = w.quantile(q, now=float(len(vals)))
            # the exact answer lies in the bucket whose upper bound the
            # histogram reported
            below = max([b for b in buckets if b < hist_q], default=0.0)
            assert below < exact_q <= hist_q, (
                f"q={q}: exact {exact_q} outside histogram bucket "
                f"({below}, {hist_q}]")

    def test_fraction_over_and_burn_rate(self):
        w = WindowedQuantiles(window_s=1e9)
        for i, v in enumerate([0.1] * 95 + [5.0] * 5):
            w.observe(v, t=float(i))
        assert w.fraction_over(1.0, now=100.0) == pytest.approx(0.05)
        slo = SloConfig(ttft_s=1.0, target=0.99)
        assert slo.budget == pytest.approx(0.01)
        assert slo.burn_rate(0.05) == pytest.approx(5.0)
        assert slo.exceeded(0.05)
        assert not slo.exceeded(0.005)
        assert w.fraction_over(1.0, now=1e9 + 101.0) == 0.0  # empty

    def test_validation(self):
        with pytest.raises(ValueError):
            WindowedQuantiles(window_s=0)
        with pytest.raises(ValueError):
            SloConfig(ttft_s=0.0)
        with pytest.raises(ValueError):
            SloConfig(ttft_s=1.0, target=1.0)


class TestRequestLog:
    def _rec(self, rid, ttft=0.1, stall=0.05):
        return {"rid": rid, "ttft_s": ttft, "latency_s": ttft + 0.2,
                "queue_wait_s": 0.01, "prefill_own_s": 0.02,
                "prefill_stall_s": stall, "decode_s": 0.2,
                "finish_reason": "eos", "tokens": 8}

    def test_ring_bounded_no_unbounded_growth(self):
        log = oreq.RequestLog(capacity=16)
        for i in range(1000):
            log.add(self._rec(i))
        assert len(log) == 16
        assert log.evicted() == 1000 - 16
        assert [r["rid"] for r in log.records()] == list(range(984, 1000))

    def test_slowest_orders_and_attributes(self):
        log = oreq.RequestLog(capacity=64)
        for i, ttft in enumerate([0.1, 0.9, 0.5]):
            log.add(self._rec(i, ttft=ttft))
        slow = log.slowest(2)
        assert [r["rid"] for r in slow] == [1, 2]
        a = slow[0]["attribution"]
        assert a["dominant"] in ("queue_wait", "prefill_own",
                                 "prefill_stall", "decode")
        assert sum(a["fractions"].values()) == pytest.approx(1.0)

    def test_attribute_dominant_and_empty(self):
        a = oreq.attribute({"queue_wait_s": 0.01, "prefill_own_s": 0.0,
                            "prefill_stall_s": 0.5, "decode_s": 0.1})
        assert a["dominant"] == "prefill_stall"
        assert a["ttft_dominant"] == "prefill_stall"
        assert a["fractions"]["prefill_stall_s"] > 0.8
        empty = oreq.attribute({})
        assert empty["dominant"] == "none"
        assert empty["ttft_dominant"] == "none"

    def test_ttft_dominance_ignores_decode(self):
        """A long generation must not mask the scheduling artifact:
        decode dominates the lifetime, prefill_stall dominates TTFT."""
        a = oreq.attribute({"queue_wait_s": 0.02, "prefill_own_s": 0.01,
                            "prefill_stall_s": 0.3, "decode_s": 2.0})
        assert a["dominant"] == "decode"
        assert a["ttft_dominant"] == "prefill_stall"

    def test_summary_counts(self):
        log = oreq.RequestLog(capacity=8)
        log.add(self._rec(0))
        log.add(dict(self._rec(1), finish_reason="max_tokens"))
        s = log.summary()
        assert s["count"] == 2 and s["capacity"] == 8
        assert s["by_reason"] == {"eos": 1, "max_tokens": 1}
        assert s["by_dominant_component"] == {"decode": 2}


class TestBottleneckAttribution:
    def test_input_bound(self):
        label, fr = bottleneck.attribute_step(0.08, 0.001, 0.01)
        assert label == "input_bound"
        assert fr["input"] > 0.8
        assert sum(fr.values()) == pytest.approx(1.0)

    def test_compute_bound_without_cost_model(self):
        """No FLOPs estimate: device wait is indistinguishable from
        compute — sync-dominated steps report compute_bound."""
        label, fr = bottleneck.attribute_step(0.001, 0.002, 0.2)
        assert label == "compute_bound"
        assert fr["sync"] == 0.0

    def test_sync_bound_with_cost_model(self):
        """Sync wait far beyond the modeled compute is attributable:
        stragglers/collectives, not this step's math."""
        label, fr = bottleneck.attribute_step(0.001, 0.002, 0.2,
                                              est_compute_s=0.01)
        assert label == "sync_bound"
        assert fr["sync"] > 0.8

    def test_modeled_compute_caps_at_observed_sync(self):
        # est >= sync: everything observed is explained — compute_bound
        label, fr = bottleneck.attribute_step(0.0, 0.001, 0.05,
                                              est_compute_s=1.0)
        assert label == "compute_bound"
        assert fr["sync"] == 0.0

    def test_zero_step_is_unknown(self):
        label, fr = bottleneck.attribute_step(0.0, 0.0, 0.0)
        assert label == "unknown"
        assert all(v == 0.0 for v in fr.values())

    def test_tie_breaks_toward_earlier_stage(self):
        label, _ = bottleneck.attribute_step(0.1, 0.1, 0.0)
        assert label == "input_bound"


def _smallnet():
    img = layer.data("x", paddle.data_type.dense_vector(8))
    lbl = layer.data("y", paddle.data_type.integer_value(3))
    out = layer.fc(img, 3, act=paddle.activation.Softmax())
    cost = layer.classification_cost(out, lbl, name="cost")
    params = paddle.parameters.create(cost)
    return paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.1))


class TestTrainerBottleneck:
    def test_steps_carry_classification_and_fractions(self):
        recs = []
        observe.add_report_handler(recs.append)
        tr = _smallnet()
        r = np.random.RandomState(0)
        rows = [(r.rand(8).astype("float32"), int(r.randint(3)))
                for _ in range(32)]
        tr.train(paddle.batch(lambda: iter(rows), 8), num_passes=1)
        steps = [x for x in recs if x.get("kind") == "step"]
        assert steps
        for s in steps:
            assert s["bottleneck"] in ("input_bound", "compute_bound",
                                       "sync_bound", "unknown")
            assert 0.0 <= s["frac_input"] <= 1.0
            total = s["frac_input"] + s["frac_compute"] + s["frac_sync"]
            assert total == pytest.approx(1.0, abs=0.01)
        # flight-recorder post-mortems carry the classification too
        fr = observe.default_flight_recorder().records()
        assert fr and "bottleneck" in fr[-1]
        # counter and fraction gauges are live
        c = observe.default_registry().get("train_steps_bottleneck_total")
        assert sum(cell.value for cell in c.series().values()) == \
            len(steps)
        g = observe.default_registry().get("train_bottleneck_fraction")
        assert g.value(component="input") >= 0.0

    def test_starved_input_classifies_input_bound(self):
        recs = []
        observe.add_report_handler(recs.append)
        tr = _smallnet()
        r = np.random.RandomState(0)
        rows = [(r.rand(8).astype("float32"), int(r.randint(3)))
                for _ in range(24)]

        def slow_reader():
            for row in rows:
                time.sleep(0.004)       # ~30ms/batch vs a sub-ms step
                yield row

        tr.train(paddle.batch(slow_reader, 8), num_passes=1)
        steps = [x for x in recs if x.get("kind") == "step"]
        # the compile step may classify compute_bound; the steady-state
        # majority must see the starved input
        labels = [s["bottleneck"] for s in steps[1:]]
        assert labels.count("input_bound") >= len(labels) / 2, labels


# -- engine-side lifecycle tests (tiny transformer, CPU) -------------------

def _paged_engine(batch=2, cache_len=64, block_size=8, chunk_tokens=8,
                  **kw):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer
    from paddle_tpu.observe.compile_tracker import CompileTracker
    from paddle_tpu.serving import PagedDecodeEngine
    cfg = transformer.TransformerConfig(
        vocab=40, d_model=16, n_heads=2, n_kv_heads=1, n_layers=2,
        d_ff=32, max_len=cache_len, dtype=jnp.float32, use_rope=True)
    params = transformer.init_params(jax.random.PRNGKey(0), cfg)
    return PagedDecodeEngine.from_params(
        params, cfg, batch=batch, cache_len=cache_len,
        block_size=block_size, chunk_tokens=chunk_tokens, seed=0,
        tracker=CompileTracker(), **kw)


def _lifecycle_events(trace_id):
    evs = [e for e in observe.trace_export()["traceEvents"]
           if e.get("cat") == "request" and e.get("id") == trace_id]
    return evs


class TestEngineLifecycle:
    def test_joined_lifecycle_and_ring_bounds(self, rng):
        eng = _paged_engine()
        eng.request_log = oreq.RequestLog(capacity=4)
        prefix = rng.randint(0, 40, 8).astype(np.int32)
        reqs = []
        for tail in (3, 5, 7, 4, 6, 3, 5, 7, 2, 4):
            reqs.append(eng.submit(
                np.concatenate([prefix,
                                rng.randint(0, 40, tail).astype(
                                    np.int32)]), max_new=3))
        eng.run_until_idle()
        # ring bounds: 10 requests through a capacity-4 ring
        assert len(eng.request_log) == 4
        assert eng.request_log.evicted() == 6
        # every completed request has a fully-joined lifecycle
        for r in reqs:
            assert r.finish_reason is not None
            evs = _lifecycle_events(r.trace_id)
            assert evs, f"{r.trace_id}: no lifecycle events"
            b = sum(1 for e in evs if e["ph"] == "b")
            e_ = sum(1 for e in evs if e["ph"] == "e")
            assert b == e_ >= 1, (r.trace_id, b, e_)
            names = {e["name"] for e in evs}
            assert {"request", "queued", "admitted", "prefill",
                    "first_token", "finished"} <= names
        # prefix-cache hit evidence rides the events: a later request
        # reports hit blocks at admission, the first chunk was cold
        first_evs = _lifecycle_events(reqs[0].trace_id)
        chunk = [e for e in first_evs if e["name"] == "prefill_chunk"]
        assert chunk and chunk[0]["args"]["cold_blocks"] >= 1
        # hits arrive either at admission (cache lookup) or mid-flight
        # (adoption of a concurrent same-prefix request's blocks) —
        # both carry hit-block counts on their events
        hit_evidence = 0
        for r in reqs[1:]:
            if r.prefix_hit_tokens <= 0:
                continue
            evs = _lifecycle_events(r.trace_id)
            hit_evidence += sum(
                e["args"].get("hit_blocks", 0) for e in evs
                if e["name"] in ("admitted", "prefix_adopt"))
        assert hit_evidence >= 1

    def test_victim_ttft_dominated_by_prefill_stall(self, rng):
        """The acceptance scenario, deterministically: with a decoder
        in flight and a long-prompt adversary mid-chunked-prefill, a
        just-submitted short victim's TTFT decomposes into stall behind
        the adversary's chunks (+ interleaved decode steps) — NOT queue
        wait (a slot was free) and NOT decode."""
        eng = _paged_engine(batch=3)
        # a decoding request keeps active.any() true: one chunk/step
        a = eng.submit(rng.randint(0, 40, 4).astype(np.int32),
                       max_new=24)
        for _ in range(3):
            eng.step()
        assert a.status == "running"
        adversary = eng.submit(rng.randint(0, 40, 56).astype(np.int32),
                               max_new=4)                # 7 chunks
        # max_new=1: the victim finishes at its first token, so its
        # lifetime has NO decode component at all — the stall-vs-decode
        # dominance comparison is structural, not a wall-clock race
        # between a ~1 ms stall and one (noise-prone) decode step
        victim = eng.submit(rng.randint(0, 40, 4).astype(np.int32),
                            max_new=1)
        eng.run_until_idle()
        assert adversary.finish_reason and victim.finish_reason
        rec = next(r for r in eng.request_log.records()
                   if r["rid"] == victim.rid)
        attr = oreq.attribute(rec)
        assert attr["ttft_dominant"] == "prefill_stall", (rec, attr)
        assert attr["dominant"] == "prefill_stall", (rec, attr)
        assert rec["prefill_stall_s"] > rec["queue_wait_s"]
        assert rec["prefill_stall_s"] > rec["decode_s"]

    def test_rejection_counted_and_traced(self, rng):
        eng = _paged_engine(batch=2, cache_len=32, block_size=8,
                            chunk_tokens=8)
        with pytest.raises(ValueError):
            eng.submit(rng.randint(0, 40, 40), max_new=8)   # > cache
        assert eng.metrics.get("engine_requests_rejected_total").value(
            reason="exceeds_cache") == 1
        rej = [e for e in observe.trace_export()["traceEvents"]
               if e.get("name") == "request_rejected"]
        assert rej and rej[0]["args"]["reason"] == "exceeds_cache"
        # a rejection leaves a ring record too (the requests.py
        # contract): reason in by_reason, no measured components, and
        # it never surfaces in slowest-by-latency views
        recs = eng.request_log.records()
        assert len(recs) == 1
        assert recs[0]["finish_reason"] == "rejected:exceeds_cache"
        assert oreq.attribute(recs[0])["dominant"] == "none"
        assert eng.request_log.summary()["by_reason"] == {
            "rejected:exceeds_cache": 1}
        assert eng.request_log.slowest(5, by="ttft_s") == []

    def test_degraded_healthz_and_requests_endpoint(self, rng):
        eng = _paged_engine()
        eng.configure_slo(SloConfig(ttft_s=10.0, window_s=300.0))
        eng.submit(rng.randint(0, 40, 6).astype(np.int32), max_new=3)
        eng.run_until_idle()
        assert eng.health().get("status") is None        # within SLO
        assert eng.health()["slo"]["ttft_burn_rate"] == 0.0
        # inject the breach: an SLO no real request can meet
        eng.configure_slo(SloConfig(ttft_s=1e-9, target=0.9,
                                    window_s=300.0))
        eng.submit(rng.randint(0, 40, 6).astype(np.int32), max_new=3)
        eng.run_until_idle()
        http = eng.serve()
        try:
            resp = urllib.request.urlopen(http.url + "/healthz",
                                          timeout=5)
            doc = json.loads(resp.read())
            assert resp.status == 200                    # degraded != 503
            assert doc["status"] == "degraded"
            assert "ttft_slo_burn_rate" in doc["degraded_reason"]
            assert doc["slo"]["ttft_burn_rate"] > 1.0
            rq = json.loads(urllib.request.urlopen(
                http.url + "/requests", timeout=5).read())
            assert rq["count"] == 2
            slow = rq["slowest_by_ttft"]
            assert slow and "attribution" in slow[0]
            assert slow[0]["attribution"]["dominant"] != "none"
        finally:
            http.close()
        # windowed gauges published
        g = eng.metrics.get("engine_ttft_window_seconds")
        assert g.value(q="p99") > 0
        assert eng.metrics.get("engine_slo_burn_rate").value() > 1.0

    def test_window_gauges_refresh_on_read(self, rng):
        """Window samples expire with time; the gauges must not keep
        reporting a breach after the window drains (scrape path goes
        through metrics_text / health, both of which refresh)."""
        eng = _paged_engine()
        eng.configure_slo(SloConfig(ttft_s=1e-9, target=0.9,
                                    window_s=300.0))
        eng.submit(rng.randint(0, 40, 6).astype(np.int32), max_new=3)
        eng.run_until_idle()
        assert eng.metrics.get("engine_slo_burn_rate").value() > 1.0
        # simulate every sample expiring: swap in drained estimators
        # with the same window (the engines' clocks are wall-time, so
        # tests can't wait out a real window)
        eng._win_ttft.clear()
        eng._win_tps.clear()
        eng.metrics_text()
        assert eng.metrics.get("engine_slo_burn_rate").value() == 0.0
        assert eng.metrics.get(
            "engine_ttft_window_seconds").value(q="p99") == 0.0
        assert eng.health().get("status") is None     # breach gone

    def test_slot_engine_lifecycle_joined_too(self, rng):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.models import transformer
        from paddle_tpu.observe.compile_tracker import CompileTracker
        from paddle_tpu.serving import DecodeEngine
        cfg = transformer.TransformerConfig(
            vocab=40, d_model=16, n_heads=2, n_kv_heads=1, n_layers=2,
            d_ff=32, max_len=64, dtype=jnp.float32, use_rope=True)
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        eng = DecodeEngine.from_params(params, cfg, batch=2,
                                       cache_len=32, buckets=(8, 16),
                                       seed=0, tracker=CompileTracker())
        r = eng.submit(rng.randint(0, 40, 6).astype(np.int32), max_new=3)
        eng.run_until_idle()
        evs = _lifecycle_events(r.trace_id)
        names = {e["name"] for e in evs}
        assert {"request", "queued", "admitted", "prefill",
                "prefill_chunk", "first_token", "finished"} <= names
        assert sum(1 for e in evs if e["ph"] == "b") == \
            sum(1 for e in evs if e["ph"] == "e")
        rec = eng.request_log.records()[0]
        assert rec["prefill_own_s"] > 0
        # monolithic prefill: stall is measurement slack, not a phase
        assert rec["prefill_stall_s"] < rec["ttft_s"]


class TestHealthStatusMapping:
    def test_degraded_is_200_with_status(self):
        srv = observe.HealthServer(
            registry=observe.Registry(),
            health_fn=lambda: {"status": "degraded",
                               "degraded_reason": "test"})
        try:
            resp = urllib.request.urlopen(srv.url + "/healthz",
                                          timeout=5)
            assert resp.status == 200
            doc = json.loads(resp.read())
            assert doc["status"] == "degraded"
            assert doc["degraded_reason"] == "test"
        finally:
            srv.close()

    def test_status_unhealthy_maps_503(self):
        srv = observe.HealthServer(
            registry=observe.Registry(),
            health_fn=lambda: {"status": "unhealthy"})
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.url + "/healthz", timeout=5)
            assert ei.value.code == 503
        finally:
            srv.close()

    def test_requests_route_404_without_fn(self):
        srv = observe.HealthServer(registry=observe.Registry())
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.url + "/requests", timeout=5)
            assert ei.value.code == 404
        finally:
            srv.close()


class TestStatsCliRequests:
    def test_renders_default_request_log(self, capsys):
        from paddle_tpu import cli
        observe.default_request_log().add(
            {"rid": 7, "ttft_s": 0.25, "latency_s": 0.5, "tokens": 16,
             "queue_wait_s": 0.01, "prefill_own_s": 0.02,
             "prefill_stall_s": 0.3, "decode_s": 0.15,
             "cache_hit_frac": 0.5, "finish_reason": "eos"})
        assert cli.main(["stats", "--requests", "5"]) == 0
        out = capsys.readouterr().out
        assert "r7" in out and "dominated by prefill_stall" in out
        assert "cache_hit 50%" in out


class TestRegressionSentinel:
    def _load(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "check_regression_under_test",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
                "benchmarks", "check_regression.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _write(self, path, speedup, tps, ttft, mtime):
        doc = {"serving_paged_speedup": speedup,
               "throughput": {"engine_paged": {"tokens_per_sec": tps}},
               "latency": {"engine_paged": {"ttft_p99_s": ttft}}}
        with open(path, "w") as f:
            json.dump(doc, f)
        os.utime(path, (mtime, mtime))

    def test_baseline_then_pass_then_regressed(self, tmp_path, capsys):
        mod = self._load()
        d = str(tmp_path)
        self._write(os.path.join(d, "a_serving_paged.json"),
                    1.4, 250.0, 0.5, 1000)
        assert mod.main(["--dir", d]) == 0
        assert "BASELINE" in capsys.readouterr().out
        # within the noise band: PASS
        self._write(os.path.join(d, "b_serving_paged.json"),
                    1.35, 240.0, 0.55, 2000)
        assert mod.main(["--dir", d]) == 0
        out = capsys.readouterr().out
        assert "SENTINEL: PASS" in out and "REGRESSED" not in out
        # speedup collapses past the 15% band: REGRESSED, exit 1
        self._write(os.path.join(d, "c_serving_paged.json"),
                    0.9, 235.0, 0.56, 3000)
        assert mod.main(["--dir", d]) == 1
        out = capsys.readouterr().out
        assert "serving_paged_speedup: REGRESSED" in out
        assert "SENTINEL: REGRESSED" in out

    def test_missing_figure_skips(self, tmp_path, capsys):
        mod = self._load()
        d = str(tmp_path)
        for i, name in enumerate(("a", "b")):
            with open(os.path.join(d, f"{name}_serving_paged.json"),
                      "w") as f:
                json.dump({"unrelated": 1}, f)
            os.utime(os.path.join(d, f"{name}_serving_paged.json"),
                     (1000 + i, 1000 + i))
        assert mod.main(["--dir", d]) == 0
        assert "SKIP" in capsys.readouterr().out
