"""Flash-decode Pallas kernel over the paged KV pool + fused sampling
epilogue + the PADDLE_TPU_PALLAS dispatch policy + int8-weight serving.

Contracts (ISSUE 10, mirroring how decode_step_slots was pinned):
- interpret-mode kernel bitwise-identical to the XLA paged path on
  aligned fp32 shapes, page-scramble invariance included;
- tolerance-bounded under bf16;
- fused-sampling ids matching serving/sampling.sample_tokens semantics
  (greedy + tie convention exact, top-k SET exact, categorical matching
  in distribution);
- engine output with q8 params exact vs the dequantized reference and
  logits within the documented q8 bound of fp32 (global rel-L2, the
  PR-5 deflake recipe);
- the jitted int8 decode HLO contains no loop-invariant fp32 weight
  materialization (the anti-hoist defenses hold);
- the engine's compile-count invariant survives the Pallas path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.io import lm_serving
from paddle_tpu.models import transformer
from paddle_tpu.observe.compile_tracker import CompileTracker
from paddle_tpu.ops.pallas import decode as fd
from paddle_tpu.ops.pallas import policy
from paddle_tpu.serving import PagedDecodeEngine, sampling

CFG = transformer.TransformerConfig(
    vocab=40, d_model=16, n_heads=2, n_kv_heads=1, n_layers=2, d_ff=32,
    max_len=64, dtype=jnp.float32, use_rope=True)
CFG_ABS = transformer.TransformerConfig(
    vocab=40, d_model=16, n_heads=2, n_layers=2, d_ff=32,
    max_len=64, dtype=jnp.float32, use_rope=False)
PARAMS = transformer.init_params(jax.random.PRNGKey(0), CFG)

BS = 8


def _pool_from_arena(cache, cfg):
    """Arena [L, B, T, Hkv, Dh] -> head-major flat pool [L, Hkv, M, Dh]
    with identity paging."""
    L, B, T = cache["k"].shape[:3]
    pool = {k: jnp.moveaxis(jnp.reshape(
        v, (L, B * T, cfg.kv_heads, cfg.head_dim)), 1, 2)
        for k, v in cache.items()}
    pages = np.arange(B * (T // BS), dtype=np.int32).reshape(B, T // BS)
    return pool, jnp.asarray(pages)


def _scramble(pool, pages, rng):
    """Permute physical blocks (the pool position axis is axis 2 at
    the head-major layout), remap the page table — same logical
    content at different physical placement."""
    M = pool["k"].shape[2]
    nb = M // BS
    perm = rng.permutation(nb).astype(np.int32)      # old block i -> perm[i]
    gidx = np.empty(M, np.int64)
    for i in range(nb):
        gidx[perm[i] * BS:(perm[i] + 1) * BS] = np.arange(
            i * BS, (i + 1) * BS)
    pool2 = {k: jnp.asarray(np.asarray(v)[:, :, gidx])
             for k, v in pool.items()}
    pages2 = jnp.asarray(perm[np.asarray(pages)])
    return pool2, pages2


class TestPallasPolicy:
    """One knob, tested precedence: explicit arg > env > auto."""

    def test_auto_resolves_by_backend(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_PALLAS", raising=False)
        want = "on" if jax.default_backend() == "tpu" else "off"
        assert policy.pallas_mode(None) == want
        assert policy.pallas_mode("auto") == want

    def test_env_over_auto(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
        assert policy.pallas_mode(None) == "interpret"

    def test_explicit_over_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PALLAS", "off")
        assert policy.pallas_mode("interpret") == "interpret"
        assert policy.pallas_mode("on") == "on"

    def test_invalid_value_raises(self, monkeypatch):
        with pytest.raises(ValueError, match="PADDLE_TPU_PALLAS"):
            policy.pallas_mode("fast")
        monkeypatch.setenv("PADDLE_TPU_PALLAS", "yes")
        with pytest.raises(ValueError, match="PADDLE_TPU_PALLAS"):
            policy.pallas_mode(None)

    def test_flash_attention_routes_through_policy(self, monkeypatch,
                                                   rng):
        """attention.py's old ad-hoc off-TPU check is gone: the env
        alone flips the public entry between the jnp reference and the
        (interpret) kernel; an explicit ``interpret`` arg beats the
        env."""
        from paddle_tpu.ops.pallas import attention as fa
        from paddle_tpu.parallel import ring
        q = jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32))
        ref = ring.full_attention(q, q, q, causal=True)

        class _Sentinel(Exception):
            pass

        def boom(*a, **k):
            raise _Sentinel

        monkeypatch.setattr(fa, "_reference", boom)
        monkeypatch.setenv("PADDLE_TPU_PALLAS", "off")
        with pytest.raises(_Sentinel):
            fa.flash_attention(q, q, q, causal=True)
        # env turns the kernel on; the reference is never consulted
        monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
        out = fa.flash_attention(q, q, q, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        # explicit arg wins over the env
        monkeypatch.setenv("PADDLE_TPU_PALLAS", "off")
        out = fa.flash_attention(q, q, q, causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestFlashDecodeKernel:
    @pytest.mark.parametrize("cfg", [CFG, CFG_ABS],
                             ids=["rope", "learned-pos"])
    def test_bitwise_vs_xla_paged(self, cfg, rng):
        """Aligned fp32 shapes: the interpret-mode kernel's decode step
        reproduces the XLA paged path's logits AND written cache
        bitwise (inactive rows included)."""
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        B, Tp, T = 3, 6, 32
        prompt = jnp.asarray(rng.randint(0, 40, (B, Tp)), jnp.int32)
        logits, cache = transformer.prefill(params, prompt, cfg, T)
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        pos = jnp.asarray([6, 3, 9], jnp.int32)
        active = jnp.asarray([True, False, True])
        pool, pages = _pool_from_arena(cache, cfg)
        l_xla, c_xla = transformer.decode_step_paged(
            params, pool, tok, pos, active, pages, cfg, block_size=BS,
            pallas="off")
        l_pal, c_pal = transformer.decode_step_paged(
            params, pool, tok, pos, active, pages, cfg, block_size=BS,
            pallas="interpret")
        np.testing.assert_array_equal(np.asarray(l_xla),
                                      np.asarray(l_pal))
        for leaf in ("k", "v"):
            np.testing.assert_array_equal(np.asarray(c_xla[leaf]),
                                          np.asarray(c_pal[leaf]))

    def test_page_scramble_invariance(self, rng):
        """Physical placement is invisible to the kernel: scrambled
        blocks + remapped page table decode bitwise identically, and
        still bitwise the XLA path on the same scrambled pool."""
        B, Tp, T = 2, 6, 32
        prompt = jnp.asarray(rng.randint(0, 40, (B, Tp)), jnp.int32)
        logits, cache = transformer.prefill(PARAMS, prompt, CFG, T)
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        pos = jnp.full((B,), Tp, jnp.int32)
        active = jnp.ones((B,), bool)
        pool, pages = _pool_from_arena(cache, CFG)
        l_id, _ = transformer.decode_step_paged(
            PARAMS, pool, tok, pos, active, pages, CFG, block_size=BS,
            pallas="interpret")
        pool2, pages2 = _scramble(pool, pages, rng)
        l_sc, _ = transformer.decode_step_paged(
            PARAMS, pool2, tok, pos, active, pages2, CFG, block_size=BS,
            pallas="interpret")
        np.testing.assert_array_equal(np.asarray(l_id), np.asarray(l_sc))
        l_xla, _ = transformer.decode_step_paged(
            PARAMS, pool2, tok, pos, active, pages2, CFG, block_size=BS,
            pallas="off")
        np.testing.assert_array_equal(np.asarray(l_sc),
                                      np.asarray(l_xla))

    def test_bf16_tolerance(self, rng):
        """bf16 pool: kernel vs XLA path within bf16 rounding (both
        accumulate fp32; the pool read rounds once per element)."""
        cfg = transformer.TransformerConfig(
            vocab=40, d_model=16, n_heads=2, n_kv_heads=1, n_layers=2,
            d_ff=32, max_len=64, dtype=jnp.bfloat16, use_rope=True)
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        B, Tp, T = 2, 6, 32
        prompt = jnp.asarray(rng.randint(0, 40, (B, Tp)), jnp.int32)
        logits, cache = transformer.prefill(params, prompt, cfg, T)
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        pos = jnp.full((B,), Tp, jnp.int32)
        active = jnp.ones((B,), bool)
        pool, pages = _pool_from_arena(cache, cfg)
        l_xla, _ = transformer.decode_step_paged(
            params, pool, tok, pos, active, pages, cfg, block_size=BS,
            pallas="off")
        l_pal, _ = transformer.decode_step_paged(
            params, pool, tok, pos, active, pages, cfg, block_size=BS,
            pallas="interpret")
        np.testing.assert_allclose(np.asarray(l_xla, np.float32),
                                   np.asarray(l_pal, np.float32),
                                   rtol=2e-2, atol=2e-2)

    def test_kernel_direct_tile_sweep(self, rng):
        """The raw kernel entry over every legal tile returns the same
        values (tile is a scheduling knob — pages streamed per grid
        step — not a numerics knob)."""
        B, Hkv, G, Dh, P = 2, 2, 2, 8, 4
        M = 2 * B * P * BS
        q = jnp.asarray(rng.randn(B, Hkv, G, Dh).astype(np.float32))
        k = jnp.asarray(rng.randn(Hkv, M, Dh).astype(np.float32))
        v = jnp.asarray(rng.randn(Hkv, M, Dh).astype(np.float32))
        pages = jnp.asarray(rng.permutation(M // BS)[:B * P]
                            .reshape(B, P).astype(np.int32))
        pos = jnp.asarray([13, 30], jnp.int32)
        outs = [np.asarray(fd.flash_decode_attention(
            q, k, v, pages, pos, block_size=BS, tile=t, interpret=True))
            for t in (1, 2, 4)]
        np.testing.assert_array_equal(outs[0], outs[1])
        np.testing.assert_array_equal(outs[0], outs[2])
        with pytest.raises(ValueError, match="tile"):
            fd.flash_decode_attention(q, k, v, pages, pos,
                                      block_size=BS, tile=3,
                                      interpret=True)

    def test_tile_selection_and_budget(self):
        # analytic default: pow2 divisor of P, <= 256 rows per step
        assert fd.select_decode_tile(16, 16, 64, jnp.bfloat16) == 16
        assert fd.select_decode_tile(128, 16, 64, jnp.bfloat16) == 16
        assert fd.select_decode_tile(6, 16, 64, jnp.bfloat16) == 2
        # measured table is keyed by POOL LAYOUT first (stale
        # slot-major sweep entries can never match) and wins only when
        # its advisory block size matches
        key = (fd.POOL_LAYOUT, 1 << 11, 64, "bfloat16")
        fd.MEASURED_DECODE[key] = (16, 4)
        try:
            assert fd.select_decode_tile(128, 16, 64, jnp.bfloat16) == 4
            assert fd.select_decode_tile(128, 32, 64, jnp.bfloat16) != 4
        finally:
            del fd.MEASURED_DECODE[key]
        # a pre-relayout-style key (no layout token) is dead weight
        fd.MEASURED_DECODE[(1 << 11, 64, "bfloat16")] = (16, 4)
        try:
            assert fd.select_decode_tile(128, 16, 64,
                                         jnp.bfloat16) == 16
        finally:
            del fd.MEASURED_DECODE[(1 << 11, 64, "bfloat16")]
        # budget: scalar-prefetched streaming made the working set
        # independent of the pool size M (only the slot's own span
        # lives in scratch) — a huge pool behind a serving-sized span
        # fits; a span whose V scratch alone exceeds VMEM does not
        assert fd.decode_kernel_fits(8 * 2048, 128, 16, 4, 128,
                                     jnp.bfloat16)
        assert fd.decode_kernel_fits(512 * 8192, 512, 16, 8, 256,
                                     jnp.float32)
        assert not fd.decode_kernel_fits(512 * 8192, 2048, 16, 8, 512,
                                         jnp.float32)


class TestFusedSample:
    def test_greedy_rows_exact_and_tie_first_index(self, rng):
        logits = rng.randn(3, 11).astype(np.float32)
        logits[1, 2] = logits[1, 7] = logits[1].max() + 1.0   # tie
        lg = jnp.asarray(logits)
        temp = jnp.zeros((3,), jnp.float32)
        topk = jnp.asarray([0, 4, 11], jnp.int32)
        ids = np.asarray(fd.fused_sample(lg, np.int32(5), temp, topk,
                                         interpret=True))
        ref = np.asarray(sampling.sample_tokens(
            lg, jax.random.PRNGKey(5), temp, topk))
        np.testing.assert_array_equal(ids, ref)
        assert ids[1] == 2                       # first-index tie win

    def test_topk_membership_and_disable(self, rng):
        """Sampled ids always land in the exact top-k SET (ties at the
        threshold included); k<=0 and k>=V disable filtering."""
        logits = rng.randn(4, 13).astype(np.float32)
        logits[2, 5] = logits[2, 8]              # tie at the threshold
        lg = jnp.asarray(logits)
        temp = jnp.full((4,), 0.7, jnp.float32)
        topk = jnp.asarray([3, 0, 3, 50], jnp.int32)
        f = jax.jit(lambda s: fd.fused_sample(lg, s, temp, topk,
                                              interpret=True))
        keep = []
        for b, k in enumerate((3, 0, 3, 50)):
            if k <= 0 or k >= 13:
                keep.append(set(range(13)))
            else:
                kth = np.sort(logits[b])[::-1][k - 1]
                keep.append({i for i in range(13)
                             if logits[b, i] >= kth})
        for s in range(64):
            ids = np.asarray(f(jnp.asarray(s, jnp.int32)))
            for b in range(4):
                assert int(ids[b]) in keep[b], (b, s, ids[b])

    def test_categorical_matches_distribution(self, rng):
        """Temperature sampling follows softmax(logits/t) — the hash-
        Gumbel stream differs from jax.random's per id, so the contract
        is the distribution (deterministic seeds, fixed tolerance)."""
        lg = jnp.asarray(rng.randn(1, 5).astype(np.float32))
        temp = jnp.full((1,), 0.8, jnp.float32)
        topk = jnp.zeros((1,), jnp.int32)
        f = jax.jit(lambda s: fd.fused_sample(lg, s, temp, topk,
                                              interpret=True))
        counts = np.zeros(5)
        n = 1500
        for s in range(n):
            counts[int(np.asarray(f(jnp.asarray(s, jnp.int32)))[0])] += 1
        probs = np.asarray(jax.nn.softmax(np.asarray(lg[0]) / 0.8))
        np.testing.assert_allclose(counts / n, probs, atol=0.05)


def _paged(pallas=None, params=PARAMS, **kw):
    kw.setdefault("batch", 2)
    kw.setdefault("cache_len", 32)
    kw.setdefault("block_size", 8)
    kw.setdefault("chunk_tokens", 8)
    return PagedDecodeEngine.from_params(
        params, CFG, seed=0, tracker=CompileTracker(), pallas=pallas,
        **kw)


class TestEnginePallas:
    def test_engine_outputs_match_generate_and_xla(self, rng):
        """Greedy paged-engine output through the interpret-mode kernel
        + fused epilogue == transformer.generate == the XLA-path
        engine, mixed lengths, chunked prefill included; the
        one-decode-program invariant survives."""
        prompts = [rng.randint(0, 40, n).astype(np.int32)
                   for n in (5, 9, 3, 20)]
        eng_pal = _paged(pallas="interpret")
        eng_xla = _paged(pallas="off")
        outs = {}
        for name, eng in (("pal", eng_pal), ("xla", eng_xla)):
            reqs = [eng.submit(p, max_new=6) for p in prompts]
            eng.run_until_idle()
            outs[name] = [r.output for r in reqs]
        for p, a, b in zip(prompts, outs["pal"], outs["xla"]):
            want = np.asarray(transformer.generate(
                PARAMS, jnp.asarray(p[None]), CFG, max_new=6))[0]
            np.testing.assert_array_equal(a, want)
            np.testing.assert_array_equal(b, want)
        assert eng_pal.compile_counts()["decode"] == 1
        assert eng_pal.pallas_mode == "interpret"
        assert eng_pal.health()["pallas"] == "interpret"

    def test_decode_mfu_reported(self, rng):
        """The engine knows its decode FLOPs (lowered cost analysis)
        and reports a positive mean decode MFU after a run — the
        serving_bench scoreboard field."""
        eng = _paged(pallas="off")
        assert eng.decode_flops and eng.decode_flops > 0
        eng.submit(rng.randint(0, 40, 5).astype(np.int32), max_new=4)
        eng.run_until_idle()
        mfu = eng.decode_mfu()
        assert mfu is not None and mfu > 0
        assert eng.health().get("decode_mfu", 0) > 0
        assert "engine_decode_mfu" in eng.metrics_text()


class TestOnModeFallback:
    def test_on_mode_serves_via_xla_off_tpu(self, rng):
        """``pallas="on"`` on a non-TPU backend must fall back to the
        XLA path with a once-per-mode warning, not fail the first
        compile — the dispatch gate the head-major relayout flipped
        from a constant veto (``MOSAIC_LOWERABLE``) to backend check +
        per-shape lowering probes. On a TPU backend the same gate
        returns True and the probes decide per shape."""
        import warnings
        assert fd.kernels_dispatchable("interpret") is True
        assert fd.kernels_dispatchable("off") is False
        on_tpu = jax.default_backend() == "tpu"
        fd._warned_fallback = set()
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            assert fd.kernels_dispatchable("on") is on_tpu
            # second and third resolutions must NOT warn again — the
            # engine resolves the mode once per program build, and a
            # warning per build would spam every chunk-bucket compile
            assert fd.kernels_dispatchable("on") is on_tpu
            assert fd.kernels_dispatchable("on") is on_tpu
        if not on_tpu:
            warned = [w for w in rec
                      if "falls back" in str(w.message)]
            assert len(warned) == 1, [str(w.message) for w in rec]
        prompts = [rng.randint(0, 40, n).astype(np.int32)
                   for n in (5, 20)]
        outs = {}
        for mode in ("on", "off"):
            eng = _paged(pallas=mode)
            reqs = [eng.submit(p, max_new=5) for p in prompts]
            eng.run_until_idle()
            outs[mode] = [r.output.tolist() for r in reqs]
        assert outs["on"] == outs["off"]

    def test_no_warning_spam_across_engine_lifecycle(self, rng):
        """A full pallas="on" engine run off-TPU — chunk prefill
        programs, decode, sampling epilogue — emits at most ONE
        fallback RuntimeWarning in total (once per mode), never one
        per compiled program."""
        import warnings
        if jax.default_backend() == "tpu":
            pytest.skip("off-TPU fallback path")
        fd._warned_fallback = set()
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            eng = _paged(pallas="on")
            reqs = [eng.submit(rng.randint(0, 40, n).astype(np.int32),
                               max_new=4) for n in (5, 9, 20)]
            eng.run_until_idle()
        assert all(r.output is not None for r in reqs)
        fallback = [w for w in rec
                    if issubclass(w.category, RuntimeWarning)
                    and "falls back" in str(w.message)]
        assert len(fallback) <= 1, [str(w.message) for w in fallback]


class TestLoweringProbes:
    """The MOSAIC_LOWERABLE constant became real probes: deviceless
    XLA:TPU lowering of the actual kernels, cached per shape. These run
    the probes on CPU — the same machinery ``serving_bench --tpu-check``
    asserts — so a kernel change that breaks Mosaic legality fails
    tier-1, not the first on-chip deploy."""

    def test_decode_probe_accepts_all_kv_dtypes(self):
        for kvd, dt in (("none", jnp.float32), ("int8", jnp.int8),
                        ("int4", jnp.int8)):
            assert fd.decode_lowering_ok(64, 4, BS, 1, 2,
                                         CFG.head_dim, dt,
                                         kv_dtype=kvd), kvd

    def test_sample_probe_accepts(self):
        assert fd.sample_lowering_ok(2, 40)

    def test_probe_caches_by_signature(self):
        fd._LOWERING_CACHE.clear()
        assert fd.decode_lowering_ok(64, 4, BS, 1, 2, CFG.head_dim,
                                     jnp.float32)
        n = len(fd._LOWERING_CACHE)
        assert fd.decode_lowering_ok(64, 4, BS, 1, 2, CFG.head_dim,
                                     jnp.float32)
        assert len(fd._LOWERING_CACHE) == n    # cache hit, no re-probe

    def test_probe_refuses_unlowerable_shape(self):
        """A genuinely illegal BlockSpec must come back False — the
        probe is a real gate, not a rubber stamp — and the refusal
        must leave its diagnostic in ``lowering_failures`` plus a
        RuntimeWarning (a silent XLA fallback on a real chip would be
        undiagnosable otherwise)."""
        import warnings

        def build():
            import jax.numpy as jnp

            def bad():
                from jax.experimental import pallas as pl
                # second-to-last block dim 1 against a multi-row
                # array — the exact pre-relayout violation
                return pl.pallas_call(
                    lambda x_ref, o_ref: o_ref.__setitem__(
                        ..., x_ref[...]),
                    grid=(4,),
                    in_specs=[pl.BlockSpec((4, 1, 8),
                                           lambda i: (0, i, 0))],
                    out_specs=pl.BlockSpec((4, 1, 8),
                                           lambda i: (0, i, 0)),
                    out_shape=jax.ShapeDtypeStruct((4, 4, 8),
                                                   jnp.float32),
                )(jnp.zeros((4, 4, 8), jnp.float32))

            return bad, []

        fd._LOWERING_CACHE.pop(("test-bad",), None)
        fd._LOWERING_DETAIL.pop(("test-bad",), None)
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            assert fd.mosaic_lowerable(("test-bad",), build) is False
        assert any("Mosaic lowering probe" in str(w.message)
                   for w in rec)
        assert ("test-bad",) in fd.lowering_failures("test-bad")
        # cached refusal: no second probe, no second warning
        with warnings.catch_warnings(record=True) as rec2:
            warnings.simplefilter("always")
            assert fd.mosaic_lowerable(("test-bad",), build) is False
        assert not rec2


class TestInt8Serving:
    def test_engine_q8_exact_vs_dequantized_reference(self, rng):
        """The in-scan dequant computes with bitwise the SAME live
        weights dequantize_tree would materialize, so the q8 engine's
        greedy output equals generate() over the dequantized tree
        exactly — the int8 path changes WHERE dequant happens, never
        the values."""
        from paddle_tpu.ops import q8 as ops_q8
        qp = lm_serving.quantize_lm_params(PARAMS)
        live = jax.tree_util.tree_map(
            lambda n: jnp.asarray(ops_q8.dequantize_weight(n))
            if ops_q8.is_quantized_weight(n) else n,
            qp, is_leaf=ops_q8.is_quantized_weight)
        eng = _paged(params=qp)
        prompts = [rng.randint(0, 40, n).astype(np.int32)
                   for n in (5, 9)]
        reqs = [eng.submit(p, max_new=6) for p in prompts]
        eng.run_until_idle()
        for p, r in zip(prompts, reqs):
            want = np.asarray(transformer.generate(
                live, jnp.asarray(p[None]), CFG, max_new=6))[0]
            np.testing.assert_array_equal(r.output, want)

    def test_q8_logits_within_documented_bound(self, rng):
        """Global rel-L2 of the q8 decode logits vs fp32 (PR-5 deflake
        recipe: a GLOBAL metric, not per-element): per-channel
        symmetric rounding injects <= 0.5/127 relative weight noise;
        through 2·n_layers matmuls + the vocab head that compounds to
        ~(2L+2)·0.5/127 ≈ 2.4% here — budget 5% leaves 2x slack
        without ever excusing a wrong-scale bug (which lands >> 10%)."""
        B, Tp, T = 3, 6, 32
        prompt = jnp.asarray(rng.randint(0, 40, (B, Tp)), jnp.int32)
        logits, cache = transformer.prefill(PARAMS, prompt, CFG, T)
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        pos = jnp.full((B,), Tp, jnp.int32)
        active = jnp.ones((B,), bool)
        pool, pages = _pool_from_arena(cache, CFG)
        l_fp, _ = transformer.decode_step_paged(
            PARAMS, pool, tok, pos, active, pages, CFG, block_size=BS,
            pallas="off")
        qp = lm_serving.quantize_lm_params(PARAMS)
        l_q8, _ = transformer.decode_step_paged(
            qp, pool, tok, pos, active, pages, CFG, block_size=BS,
            pallas="off")
        a, b = np.asarray(l_fp), np.asarray(l_q8)
        rel = np.linalg.norm(a - b) / np.linalg.norm(a)
        assert rel < 0.05, rel

    def test_q8_pallas_bitwise_matches_q8_xla(self, rng):
        """int8 weights and the flash-decode kernel compose: same
        logits bitwise as the q8 XLA path (fp32 aligned shapes)."""
        B, Tp, T = 2, 6, 32
        prompt = jnp.asarray(rng.randint(0, 40, (B, Tp)), jnp.int32)
        _, cache = transformer.prefill(PARAMS, prompt, CFG, T)
        tok = jnp.zeros((B,), jnp.int32)
        pos = jnp.full((B,), Tp, jnp.int32)
        active = jnp.ones((B,), bool)
        pool, pages = _pool_from_arena(cache, CFG)
        qp = lm_serving.quantize_lm_params(PARAMS)
        l_xla, _ = transformer.decode_step_paged(
            qp, pool, tok, pos, active, pages, CFG, block_size=BS,
            pallas="off")
        l_pal, _ = transformer.decode_step_paged(
            qp, pool, tok, pos, active, pages, CFG, block_size=BS,
            pallas="interpret")
        np.testing.assert_array_equal(np.asarray(l_xla),
                                      np.asarray(l_pal))

    def test_no_loop_invariant_fp32_weight_materialization(self):
        """The optimized decode HLO must carry the block weights as the
        int8 stack and dequantize per-layer INSIDE the scan: any
        f32[L, ...] tensor of a stacked weight shape would mean XLA
        hoisted a full fp32 materialization (4-byte reads per token —
        the regression the carry/barrier/loop-variant-scale defenses
        exist to prevent)."""
        qp = lm_serving.quantize_lm_params(PARAMS)
        _, decode_fn = sampling.paged_step_fns(CFG, BS, pallas="off")
        B, P = 2, 4
        pool = transformer.init_block_pool(CFG, 8, BS)
        args = (qp, pool, np.zeros(B, np.int32), np.zeros(B, np.int32),
                np.zeros(B, bool), np.zeros((B, P), np.int32),
                np.zeros(B, np.float32), np.zeros(B, np.int32),
                np.int32(0))
        hlo = jax.jit(decode_fn).lower(*args).compile().as_text()
        L, D = CFG.n_layers, CFG.d_model
        E = D + 2 * CFG.kv_heads * CFG.head_dim
        F = CFG.d_ff
        for shape in (f"f32[{L},{D},{E}]", f"f32[{L},{D},{F}]",
                      f"f32[{L},{F},{D}]", f"f32[{L},{D},{D}]"):
            assert shape not in hlo, (
                f"full-stack fp32 weights {shape} materialized — the "
                f"in-scan dequant was hoisted")
        # the int8 stack must actually ride the program
        assert f"s8[{L},{D},{E}]" in hlo
