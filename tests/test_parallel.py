"""Distribution tests on the 8-device virtual CPU mesh (the in-process
cluster strategy, SURVEY.md §4.6). DP must be numerically equivalent to
single-device training; TP shardings must produce the declared layouts."""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import evaluator, layer, parallel
from paddle_tpu.core import place
from paddle_tpu.utils.rng import KeySource


def _model(seed):
    x = layer.data("x", paddle.data_type.dense_vector(8))
    lbl = layer.data("lbl", paddle.data_type.integer_value(3))
    h = layer.fc(x, 16, act=paddle.activation.Relu(), name="h")
    out = layer.fc(h, 3, act=paddle.activation.Softmax(), name="o")
    cost = layer.classification_cost(out, lbl, name="cost")
    params = paddle.parameters.create(cost, KeySource(seed))
    return cost, params


def _data(n=32):
    rng = np.random.RandomState(0)
    return [(rng.randn(8).astype(np.float32), int(rng.randint(3)))
            for _ in range(n)]


def _train(parallel_cfg, seed=11, passes=2):
    cost, params = _model(seed)
    tr = paddle.trainer.SGD(cost=cost, parameters=params,
                            update_equation=paddle.optimizer.Momentum(
                                momentum=0.9, learning_rate=0.1),
                            parallel=parallel_cfg)
    costs = []
    tr.train(reader=paddle.batch(lambda: iter(_data()), 16),
             num_passes=passes,
             event_handler=lambda e: costs.append(e.cost) if isinstance(
                 e, paddle.event.EndIteration) else None)
    return costs, tr


def test_dp_matches_single_device():
    """Data-parallel over 8 devices must match single-device numerics —
    the correctness bar the reference's test_CompareSparse.cpp set for
    remote-vs-local training."""
    costs_single, _ = _train(None)
    costs_dp, tr = _train(parallel.data_parallel(place.default_mesh()))
    np.testing.assert_allclose(costs_single, costs_dp, rtol=2e-4, atol=1e-5)
    # params are replicated across the mesh
    sh = tr.parameters.values["h.w"].sharding
    assert sh.is_fully_replicated


def test_tp_fc_column_sharding():
    mesh = place.make_mesh((4, 2), (parallel.AXIS_DATA, parallel.AXIS_MODEL))
    cfg = parallel.DistConfig(mesh, param_rules=[
        parallel.fc_column_rule(r"^h\.w$")])
    costs_tp, tr = _train(cfg)
    costs_single, _ = _train(None)
    np.testing.assert_allclose(costs_single, costs_tp, rtol=2e-4, atol=1e-5)
    spec = tr.parameters.values["h.w"].sharding.spec
    assert spec == jax.sharding.PartitionSpec(None, parallel.AXIS_MODEL)


def test_sharded_embedding_training():
    mesh = place.make_mesh((2, 4), (parallel.AXIS_DATA, parallel.AXIS_MODEL))
    cfg = parallel.DistConfig(mesh, param_rules=[
        parallel.embedding_vocab_rule(r"^emb\.w$")])
    words = layer.data("words", paddle.data_type.integer_value_sequence(40))
    lbl = layer.data("lbl", paddle.data_type.integer_value(2))
    emb = layer.embedding(words, 8, name="emb")
    pooled = layer.pool(emb, name="pool")
    out = layer.fc(pooled, 2, act=paddle.activation.Softmax(), name="o")
    cost = layer.classification_cost(out, lbl, name="cost")
    params = paddle.parameters.create(cost, KeySource(3))
    tr = paddle.trainer.SGD(cost=cost, parameters=params,
                            update_equation=paddle.optimizer.Adam(
                                learning_rate=1e-2),
                            parallel=cfg)
    rng = np.random.RandomState(1)
    data = [([int(w) for w in rng.randint(0, 40, 5)], int(i % 2))
            for i in range(16)]
    costs = []
    tr.train(reader=paddle.batch(lambda: iter(data), 8), num_passes=3,
             event_handler=lambda e: costs.append(e.cost) if isinstance(
                 e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0]
    spec = tr.parameters.values["emb.w"].sharding.spec
    assert spec[0] == parallel.AXIS_MODEL


def test_dryrun_multichip_entry():
    import __graft_entry__ as g
    g.dryrun_multichip(8)
