"""The async, checkpointable input pipeline (paddle_tpu/pipeline/):
sources, stage snapshots, exact mid-epoch resume (the preemption
contract), trainer integration, reader-decorator robustness, and the
feed bench's overlap claim."""

import os
import pickle
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu import pipeline
from paddle_tpu.io import checkpoint as ckpt_io
from paddle_tpu.reader import decorator as rdec
from paddle_tpu.runtime import recordio
from paddle_tpu.utils.flags import GLOBAL_FLAGS
from paddle_tpu.utils.rng import KeySource


def _write_shards(tmp_path, n_shards=2, chunks=3, per_chunk=8, dim=6):
    """Recordio shards of (features f32[dim], label) samples with
    globally unique feature[0] so streams compare exactly."""
    paths, gid = [], 0
    r = np.random.RandomState(7)
    for s in range(n_shards):
        p = str(tmp_path / f"part-{s:05d}.rio")
        with recordio.Writer(p, records_per_chunk=per_chunk) as w:
            for _ in range(chunks * per_chunk):
                feat = r.rand(dim).astype(np.float32)
                feat[0] = gid          # unique id rides in the sample
                w.write((feat, int(gid % 4)))
                gid += 1
        paths.append(p)
    return paths


def _ids(batches):
    """Flatten a batch stream to the unique-id sequence."""
    return [int(s[0][0]) for b in batches for s in b]


class TestSources:
    def test_reader_source_resume_skips(self):
        src = pipeline.ReaderSource(lambda: iter(range(10)))
        it = iter(src)
        got = [next(it) for _ in range(4)]
        st = src.state_dict()
        assert st == {"kind": "reader", "epoch": 0, "offset": 4}
        it.close()
        src2 = pipeline.ReaderSource(lambda: iter(range(10)))
        src2.load_state_dict(st)
        assert list(iter(src2)) == list(range(4, 10))
        # epoch rolled over
        assert src2.state_dict() == {"kind": "reader", "epoch": 1,
                                     "offset": 0}

    def test_reader_source_shrunk_data_is_loud(self):
        src = pipeline.ReaderSource(lambda: iter(range(3)))
        src.load_state_dict({"kind": "reader", "epoch": 0, "offset": 7})
        with pytest.raises(RuntimeError, match="exhausted before"):
            list(iter(src))

    def test_shard_source_covers_all_records_per_epoch(self, tmp_path):
        paths = _write_shards(tmp_path)
        src = pipeline.ShardSource(paths, shuffle_chunks=True, seed=3)
        assert src.num_records() == 48
        epoch0 = [int(s[0][0]) for s in iter(src)]
        assert sorted(epoch0) == list(range(48))
        epoch1 = [int(s[0][0]) for s in iter(src)]
        assert sorted(epoch1) == list(range(48))
        # chunk permutations differ across epochs
        assert epoch0 != epoch1

    def test_shard_source_mid_chunk_resume_exact(self, tmp_path):
        paths = _write_shards(tmp_path)
        src = pipeline.ShardSource(paths, shuffle_chunks=True, seed=3)
        it = iter(src)
        head = [next(it) for _ in range(13)]   # mid-chunk (per_chunk=8)
        st = src.state_dict()
        it.close()
        src2 = pipeline.ShardSource(paths, shuffle_chunks=True, seed=3)
        src2.load_state_dict(st)
        resumed = [int(s[0][0]) for s in iter(src2)]
        full = [int(s[0][0]) for s in iter(
            pipeline.ShardSource(paths, shuffle_chunks=True, seed=3))]
        assert [int(s[0][0]) for s in head] == full[:13]
        assert resumed == full[13:]

    def test_source_kind_mismatch_is_loud(self):
        src = pipeline.ReaderSource(lambda: iter(range(3)))
        with pytest.raises(Exception, match="state mismatch"):
            src.load_state_dict({"kind": "shards", "epoch": 0,
                                 "chunk_pos": 0, "record_pos": 0})

    def test_master_source_streams_task_records(self, tmp_path):
        from paddle_tpu.runtime import master as m
        path = str(tmp_path / "data.rio")
        recordio.write_records(path, list(range(20)), chunk_records=5)
        svc = m.MasterService(lease_seconds=30)
        svc.set_dataset([path], chunks_per_task=1)
        try:
            src = pipeline.MasterSource(m.MasterClient(service=svc))
            with pipeline.Pipeline(src, batch_size=4) as p:
                got = [x for b in iter(p) for x in b]
            assert sorted(got) == list(range(20))
            assert src.state_dict()["records"] == 20
        finally:
            svc.close()


class TestStages:
    def test_transform_ordered_despite_uneven_latency(self):
        def fn(x):
            time.sleep(0.02 if x % 3 == 0 else 0.0)
            return x * 2
        with pipeline.Pipeline(lambda: iter(range(24)), transform=fn,
                               transform_workers=4, batch_size=6) as p:
            out = [x for b in iter(p) for x in b]
        assert out == [x * 2 for x in range(24)]

    def test_transform_exception_reraises_at_next(self):
        def fn(x):
            if x == 7:
                raise ValueError("xform boom")
            return x
        with pipeline.Pipeline(lambda: iter(range(20)), transform=fn,
                               transform_workers=2, batch_size=4) as p:
            with pytest.raises(ValueError, match="xform boom"):
                list(iter(p))

    def test_drop_last_tail_dies_with_its_epoch(self):
        # 10 samples / batch 4: epochs must yield [0..3],[4..7] and DROP
        # [8,9] — not leak the tail into the next epoch's first batch
        with pipeline.Pipeline(lambda: iter(range(10)),
                               batch_size=4) as p:
            e1, e2 = list(iter(p)), list(iter(p))
        assert e1 == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert e2 == e1

    def test_drop_last_false_emits_ragged_tail(self):
        with pipeline.Pipeline(lambda: iter(range(10)), batch_size=4,
                               drop_last=False) as p:
            e1 = list(iter(p))
        assert e1 == [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]

    def test_shuffle_seeded_and_complete(self):
        def run():
            with pipeline.Pipeline(lambda: iter(range(40)),
                                   shuffle_size=8, seed=11,
                                   batch_size=5) as p:
                return [x for b in iter(p) for x in b]
        a, b = run(), run()
        assert a == b                       # seeded → reproducible
        assert a != list(range(40))         # actually shuffled
        assert sorted(a) == list(range(40))  # a permutation, no loss


class TestPipeline:
    def test_source_exception_reraises_not_hangs(self):
        def bad():
            yield from range(5)
            raise RuntimeError("src boom")
        with pipeline.Pipeline(bad, batch_size=2) as p:
            with pytest.raises(RuntimeError, match="src boom"):
                list(iter(p))

    def test_convert_exception_reraises(self):
        with pipeline.Pipeline(lambda: iter(range(8)), batch_size=2,
                               convert=lambda b: 1 / 0) as p:
            with pytest.raises(ZeroDivisionError):
                list(iter(p))

    def test_close_is_idempotent_and_final(self):
        p = pipeline.Pipeline(lambda: iter(range(100)), batch_size=4)
        it = iter(p)
        next(it)
        p.close()
        p.close()
        with pytest.raises(pipeline.PipelineClosed):
            list(iter(p))

    def test_backpressure_bounds_staging(self):
        produced = []

        def src():
            for i in range(1000):
                produced.append(i)
                yield i
        p = pipeline.Pipeline(src, batch_size=1, prefetch=3,
                              device_depth=2)
        it = iter(p)
        next(it)
        time.sleep(0.3)                    # let the producer run ahead
        # bounded: ring(3) + device(2) + in-flight slack, NOT all 1000
        assert len(produced) < 50
        p.close()

    def test_abandoned_epoch_then_reiterate_not_poisoned(self):
        """Abandoning an epoch iterator mid-stream (no state restore)
        must not poison the next iteration: the transform stage's
        in-flight futures are cancelled and their raws re-submitted —
        NOT drained as cancelled futures (CancelledError) or replayed
        twice. Batches already staged in the ring/device queues are
        discarded with the abandoned iteration (exact continuation is
        load_state_dict's job), so the continuation resumes in order,
        duplicate-free, with at most a bounded staging gap."""
        with pipeline.Pipeline(lambda: iter(range(30)),
                               transform=lambda x: x * 2,
                               transform_workers=2, batch_size=2,
                               prefetch=2) as p:
            it = iter(p)
            first = [next(it) for _ in range(2)]
            it.close()                     # abandoned epoch
            rest = list(iter(p))           # continue without restore
        got = [x for b in first + rest for x in b]
        full = [x * 2 for x in range(30)]
        assert got[:4] == full[:4]
        assert sorted(set(got)) == got     # in order, no duplicates
        # suffix intact from the resume point; only a bounded staging
        # gap (ring + device buffer + transform window + batcher) lost
        resume_at = full.index(rest[0][0])
        assert got[4:] == full[resume_at:]
        assert resume_at - 4 <= 2 * (2 + 2) + 4 + 2

    def test_track_state_off_skips_snapshots_and_refuses(self):
        with pipeline.Pipeline(lambda: iter(range(8)), batch_size=2,
                               track_state=False) as p:
            assert len(list(iter(p))) == 4
            with pytest.raises(Exception, match="track_state=False"):
                p.state_dict()

    def test_feed_metrics_populated(self):
        from paddle_tpu.observe import metrics as om
        with pipeline.Pipeline(lambda: iter(range(12)), batch_size=3,
                               name="mtest") as p:
            n = len(list(iter(p)))
        assert n == 4
        text = om.default_registry().render_prometheus()
        assert "pipeline_batches_total" in text
        assert "feed_wait_seconds_total" in text
        assert 'pipeline="mtest"' in text


class TestExactMidEpochResume:
    """The preemption contract: checkpoint at batch k, kill, restore —
    the resumed stream is identical to an uninterrupted run (shuffle on,
    multi-shard, parallel transform on)."""

    def _make(self, paths):
        return pipeline.Pipeline(
            pipeline.ShardSource(paths, shuffle_chunks=True, seed=5),
            transform=lambda s: (s[0] * 2.0, s[1]),
            transform_workers=3, shuffle_size=10, seed=9, batch_size=4,
            prefetch=3)

    # k=9/10 land in the end-of-epoch tail-drain window (transform
    # window + shuffle buffer flushing after the source exhausted) —
    # the snapshot then carries pending raws WITH a rolled source
    # cursor, the case the preload_only restore path exists for
    @pytest.mark.parametrize("k", [1, 5, 9, 10, 11])
    def test_resume_bitwise_identical(self, tmp_path, k):
        paths = _write_shards(tmp_path)
        # uninterrupted truth: two full epochs
        with self._make(paths) as p:
            full = list(iter(p)) + list(iter(p))
        # interrupted run: consume k batches, snapshot, abandon (a kill:
        # no clean close of the iterator)
        p2 = self._make(paths)
        it = iter(p2)
        head = [next(it) for _ in range(k)]
        st = pickle.loads(pickle.dumps(p2.state_dict()))  # survives disk
        p2.close()
        # restored pipeline continues on the exact next batch, through
        # the epoch boundary
        p3 = self._make(paths)
        p3.load_state_dict(st)
        with p3:
            resumed = list(iter(p3)) + list(iter(p3))
        want = full[k:]
        assert _ids(head) == _ids(full[:k])
        got, expect = _ids(resumed), _ids(want)
        assert got == expect, f"resume diverged at k={k}"
        # and the transformed payloads match bit-for-bit
        for rb, wb in zip(resumed, want):
            for rs, ws in zip(rb, wb):
                np.testing.assert_array_equal(rs[0], ws[0])
                assert rs[1] == ws[1]


class TestCheckpointCarry:
    def test_save_and_load_pipeline_state(self, tmp_path):
        d = str(tmp_path / "ck")
        state = {"version": 1, "source": {"kind": "reader", "epoch": 2,
                                          "offset": 17},
                 "pending": [np.arange(3)], "shuffle": None,
                 "batch": {"partial": [], "batches": 40}}
        path = ckpt_io.save_checkpoint(d, 8, {"w": np.zeros((2, 2))},
                                       pipeline_state=state)
        got = ckpt_io.load_pipeline_state(path)
        assert got["source"] == state["source"]
        assert got["batch"] == state["batch"]
        np.testing.assert_array_equal(got["pending"][0], np.arange(3))
        # model groups still load, and a stateless checkpoint reads None
        step, p, _, _ = ckpt_io.load_checkpoint(path, {"w": np.ones((2, 2))})
        assert step == 8
        p2 = ckpt_io.save_checkpoint(d, 9, {"w": np.zeros((2, 2))})
        assert ckpt_io.load_pipeline_state(p2) is None

    def test_async_checkpointer_carries_frozen_snapshot(self, tmp_path):
        d = str(tmp_path / "ack")
        state = {"cursor": 5}
        ck = ckpt_io.AsyncCheckpointer(d)
        try:
            ck.save(3, {"w": np.ones(2)}, pipeline_state=state)
            state["cursor"] = 999          # mutate AFTER save: must not leak
            ck.wait()
        finally:
            ck.close()
        got = ckpt_io.load_pipeline_state(ckpt_io.latest_checkpoint(d))
        assert got == {"cursor": 5}


class TestTrainerMidEpochPreemption:
    """End-to-end: SGD.train over a Pipeline with checkpointing, killed
    mid-epoch, resumes on the exact next batch — the resumed loss
    sequence equals the uninterrupted run's (loss is a deterministic
    function of (params, batch), so equal losses ⇒ equal batches)."""

    def _build(self):
        x = layer.data("pl_x", paddle.data_type.dense_vector(6))
        lbl = layer.data("pl_l", paddle.data_type.integer_value(4))
        out = layer.fc(x, 4, act=paddle.activation.Softmax(),
                       name="pl_out")
        cost = layer.classification_cost(out, lbl, name="pl_cost")
        params = paddle.parameters.create(cost, KeySource(3))
        tr = paddle.trainer.SGD(cost=cost, parameters=params,
                                update_equation=paddle.optimizer.Momentum(
                                    learning_rate=0.05))
        return tr

    def _pipe(self, paths):
        return pipeline.Pipeline(
            pipeline.ShardSource(paths, shuffle_chunks=True, seed=2),
            shuffle_size=12, seed=4, batch_size=8, prefetch=2)

    def test_preempt_restore_identical_stream(self, tmp_path):
        paths = _write_shards(tmp_path, n_shards=2, chunks=2, per_chunk=8)
        d = str(tmp_path / "ck")
        init = None

        def losses_of(tr, pipe, num_passes, ckpt_dir=None, stop_at=None):
            seen = []

            def h(ev):
                if isinstance(ev, paddle.event.EndIteration):
                    seen.append(ev.cost)
                    if stop_at is not None and len(seen) == stop_at:
                        raise KeyboardInterrupt("preempt")
            try:
                tr.train(reader=pipe, num_passes=num_passes,
                         event_handler=h, checkpoint_dir=ckpt_dir)
            except KeyboardInterrupt:
                pass
            return seen

        # uninterrupted truth: 2 epochs x 4 batches from shared init
        tr = self._build()
        init = {k: np.asarray(v).copy()
                for k, v in tr.parameters.values.items()}
        with self._pipe(paths) as p:
            full = losses_of(tr, p, num_passes=2)
        assert len(full) == 8

        old = GLOBAL_FLAGS.get("checkpoint_period", 0)
        GLOBAL_FLAGS.set("checkpoint_period", 2)
        try:
            # preempted run from the SAME init: dies after batch 3
            # (checkpoint landed at step 2)
            import jax.numpy as jnp
            tr2 = self._build()
            tr2.parameters.values = {k: jnp.asarray(v)
                                     for k, v in init.items()}
            p2 = self._pipe(paths)
            part = losses_of(tr2, p2, num_passes=2, ckpt_dir=d,
                             stop_at=3)
            p2.close()
            assert len(part) == 3
            np.testing.assert_allclose(part, full[:3], rtol=1e-6)
            latest = ckpt_io.latest_checkpoint(d)
            assert latest and latest.endswith("00000002")
            assert ckpt_io.load_pipeline_state(latest) is not None

            # restore: fresh trainer + fresh pipeline adopt the
            # checkpoint (params AND stream position) and continue on
            # batch index 2 — mid-epoch, shuffle on, across the epoch
            # boundary into pass 2
            tr3 = self._build()
            with self._pipe(paths) as p3:
                resumed = losses_of(tr3, p3, num_passes=2, ckpt_dir=d)
            np.testing.assert_allclose(resumed, full[2:], rtol=1e-6,
                                       err_msg="resumed stream diverged")
        finally:
            GLOBAL_FLAGS.set("checkpoint_period", old)


class TestReaderDecoratorRobustness:
    """The buffered/xmap satellite: worker exceptions reach the
    consumer; closing a generator mid-stream joins the threads (the
    conftest leak guard enforces the join on every test here)."""

    def test_buffered_propagates_source_exception(self):
        def bad():
            yield 1
            raise RuntimeError("boom")
        r = rdec.buffered(bad, 4)
        got = []
        with pytest.raises(RuntimeError, match="boom"):
            for x in r():
                got.append(x)
        assert got == [1]                  # prefix delivered, then raise

    def test_buffered_partial_iteration_joins_thread(self):
        r = rdec.buffered(lambda: iter(range(100000)), 4)
        it = r()
        assert next(it) == 0
        it.close()                          # guard asserts no leak

    def test_xmap_source_exception_propagates_not_hangs(self):
        def bad():
            yield from range(3)
            raise RuntimeError("src died")
        r = rdec.xmap_readers(lambda x: x, bad, 3, 4)
        with pytest.raises(RuntimeError, match="src died"):
            list(r())

    def test_xmap_mapper_exception_propagates(self):
        def m(x):
            if x == 5:
                raise ValueError("map boom")
            return x
        r = rdec.xmap_readers(m, lambda: iter(range(10)), 2, 4)
        with pytest.raises(ValueError, match="map boom"):
            list(r())

    def test_xmap_ordered_complete_and_partial_close(self):
        r = rdec.xmap_readers(lambda x: x + 1, lambda: iter(range(50)),
                              3, 8, order=True)
        assert list(r()) == list(range(1, 51))
        it = r()
        next(it)
        it.close()                          # guard asserts no leak

    def test_xmap_unordered_complete(self):
        r = rdec.xmap_readers(lambda x: x + 1, lambda: iter(range(50)),
                              3, 8)
        assert sorted(r()) == list(range(1, 51))


class TestFeedBenchOverlap:
    def test_pipelined_beats_sync_on_input_bound_workload(self, tmp_path):
        """The acceptance measurement, tier-1 sized: with a 25 ms/batch
        host input cost and a cheap device step, the pipelined feed must
        produce a lower per-step wall time than the synchronous feed."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "feed_bench_under_test",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "benchmarks",
                "feed_bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        trail = str(tmp_path / "feed.jsonl")
        res = mod.main(["--workload", "synthetic", "--compare",
                        "--batch", "32", "--steps", "10", "--warmup", "2",
                        "--feed-ms", "25", "--prefetch", "3",
                        f"--metrics-out={trail}"])
        sync_ms = res["sync"]["value"]
        pipe_ms = res["pipelined"]["value"]
        assert sync_ms >= 25.0              # input-bound as constructed
        assert pipe_ms < sync_ms, (
            f"pipelined feed ({pipe_ms} ms) did not beat sync "
            f"({sync_ms} ms)")
        assert res["speedup"]["value"] > 1.0
        with open(trail) as f:
            lines = [__import__("json").loads(l) for l in f]
        assert any(r["metric"] == "pipelined_feed_speedup" for r in lines)
