"""Op test harness: numpy-reference forward checks + numeric gradient checks.

Mirrors the reference's OpTest (python/paddle/v2/framework/tests/op_test.py:
check_output at :315, get_numeric_gradient central-difference at :80,
check_grad at :341) — but autodiff correctness here means jax.grad vs numeric
gradients, replacing per-op hand-written Grad kernels as the thing under test.
"""

import jax
import jax.numpy as jnp
import numpy as np


def check_forward(fn, args, expected, rtol=1e-5, atol=1e-5, jit=True):
    """Run fn (optionally jitted) and compare to a numpy reference."""
    f = jax.jit(fn) if jit else fn
    out = f(*args)
    np.testing.assert_allclose(np.asarray(out, np.float64), expected,
                               rtol=rtol, atol=atol)
    return out


def numeric_grad(fn, args, wrt=0, eps=1e-3):
    """Central-difference gradient of sum(fn(*args)) wrt args[wrt]
    (reference: op_test.py:80 get_numeric_gradient)."""
    args = [np.asarray(a, np.float64) if np.issubdtype(np.asarray(a).dtype, np.floating)
            else np.asarray(a) for a in args]
    x = args[wrt]
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gflat = g.reshape(-1)

    def f(xv):
        a = list(args)
        a[wrt] = xv
        return float(np.sum(np.asarray(fn(*[jnp.asarray(v, jnp.float32) if
                                            np.issubdtype(v.dtype, np.floating)
                                            else jnp.asarray(v) for v in a]),
                                       np.float64)))

    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        hi = f(x)
        flat[i] = orig - eps
        lo = f(x)
        flat[i] = orig
        gflat[i] = (hi - lo) / (2 * eps)
    return g


def check_grad(fn, args, wrt=0, rtol=2e-2, atol=2e-3, eps=1e-3):
    """jax.grad of sum(fn) vs central differences (reference: op_test.py:341)."""
    def scalar_fn(*a):
        return jnp.sum(fn(*a))

    jargs = [jnp.asarray(a, jnp.float32) if np.issubdtype(np.asarray(a).dtype,
                                                          np.floating)
             else jnp.asarray(a) for a in args]
    analytic = np.asarray(jax.grad(scalar_fn, argnums=wrt)(*jargs), np.float64)
    numeric = numeric_grad(fn, args, wrt, eps)
    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol)
    return analytic
