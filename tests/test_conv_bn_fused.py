"""Fused conv+BN op (ops/conv_bn.py — XLA-level composition with a
closed-form BN VJP) vs the unfused conv2d + batch_norm_train composition.

The round-3 Pallas streaming-stats kernels were retired in round 5 after
the on-chip A/B measured them at 0.43-0.59x of this plain-XLA path (see
ops/conv_bn.py docstring); these tests cover the surviving op."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import conv as ops_conv
from paddle_tpu.ops import conv_bn as fused
from paddle_tpu.ops import norm as ops_norm


class TestFusedConvBN:
    def _compose_ref(self, x, w, gamma, beta, rm, rv, stride):
        y = ops_conv.conv2d(x, w, stride=stride, padding="SAME")
        return ops_norm.batch_norm_train(y, gamma, beta, rm, rv,
                                        momentum=0.9, eps=1e-5)

    @pytest.mark.parametrize("ksize,stride", [(1, 1), (1, 2), (3, 1)])
    def test_forward_matches_composition(self, rng, ksize, stride):
        n, h, w_, c, k = 2, 8, 8, 8, 16
        x = jnp.asarray(rng.randn(n, h, w_, c).astype(np.float32))
        w = jnp.asarray(
            rng.randn(ksize, ksize, c, k).astype(np.float32) * 0.2)
        gamma = jnp.asarray(rng.rand(k).astype(np.float32) + 0.5)
        beta = jnp.asarray(rng.randn(k).astype(np.float32) * 0.1)
        rm = jnp.zeros((k,), jnp.float32)
        rv = jnp.ones((k,), jnp.float32)
        out, nm, nv = fused.conv_bn_train(
            x, w, gamma, beta, rm, rv, stride=stride, momentum=0.9,
            eps=1e-5)
        ref, rnm, rnv = self._compose_ref(x, w, gamma, beta, rm, rv,
                                          stride)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(nm), np.asarray(rnm),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(nv), np.asarray(rnv),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("ksize,stride", [(1, 1), (3, 1)])
    def test_grads_match_composition(self, rng, ksize, stride):
        n, h, w_, c, k = 2, 6, 6, 4, 8
        x = rng.randn(n, h, w_, c).astype(np.float32)
        w = rng.randn(ksize, ksize, c, k).astype(np.float32) * 0.2
        gamma = rng.rand(k).astype(np.float32) + 0.5
        beta = rng.randn(k).astype(np.float32) * 0.1
        rm = jnp.zeros((k,), jnp.float32)
        rv = jnp.ones((k,), jnp.float32)
        tgt = rng.randn(n, h // stride, w_ // stride, k).astype(np.float32)

        def loss_fused(x_, w_, g_, b_):
            out, _, _ = fused.conv_bn_train(
                jnp.asarray(x_), jnp.asarray(w_), jnp.asarray(g_),
                jnp.asarray(b_), rm, rv, stride=stride)
            return jnp.mean((out - tgt) ** 2)

        def loss_ref(x_, w_, g_, b_):
            out, _, _ = self._compose_ref(
                jnp.asarray(x_), jnp.asarray(w_), jnp.asarray(g_),
                jnp.asarray(b_), rm, rv, stride)
            return jnp.mean((out - tgt) ** 2)

        gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, w, gamma, beta)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, w, gamma, beta)
        for name, a, b in zip("xwgb", gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-4,
                                       err_msg=f"d{name}")

    def test_infer_path_matches_bn_infer(self, rng):
        n, h, w_, c, k = 2, 6, 6, 4, 8
        x = jnp.asarray(rng.randn(n, h, w_, c).astype(np.float32))
        w = jnp.asarray(rng.randn(1, 1, c, k).astype(np.float32))
        gamma = jnp.ones((k,), jnp.float32)
        beta = jnp.zeros((k,), jnp.float32)
        rm = jnp.asarray(rng.randn(k).astype(np.float32) * 0.1)
        rv = jnp.asarray(rng.rand(k).astype(np.float32) + 0.5)
        got = fused.conv_bn_infer(x, w, gamma, beta, rm, rv)
        y = ops_conv.conv2d(x, w, stride=1, padding="SAME")
        want = ops_norm.batch_norm_infer(y, gamma, beta, rm, rv)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestFusedLayerAndModel:
    def test_layer_matches_unfused_composition(self, rng):
        """layer.img_conv_bn with weights copied from an img_conv +
        batch_norm pair must produce identical training outputs."""
        import paddle_tpu as paddle
        from paddle_tpu import layer
        from paddle_tpu.topology import Topology, Value
        from paddle_tpu.utils.rng import KeySource
        dt = paddle.data_type

        def build(fused):
            x = layer.data("x", dt.dense_vector(8 * 8 * 6))
            if fused:
                out = layer.img_conv_bn(x, 3, 12, num_channels=6,
                                        stride=1, padding="SAME",
                                        act=paddle.activation.Relu(),
                                        name="f", img_size=8)
            else:
                c = layer.img_conv(x, 3, 12, num_channels=6, stride=1,
                                   padding=1, act=None, bias_attr=False,
                                   name="c", img_size=8)
                out = layer.batch_norm(c, act=paddle.activation.Relu(),
                                       name="b")
            topo = Topology(out)
            params = paddle.parameters.create(out, KeySource(3))
            return out.name, topo.compile(), params

        fname, ffwd, fparams = build(True)
        uname, ufwd, uparams = build(False)
        # identical weights across the two graphs
        fparams.values["f.w"] = uparams.values["c.w"]
        fparams.values["f.gamma"] = uparams.values["b.gamma"]
        fparams.values["f.beta"] = uparams.values["b.beta"]
        xv = rng.randn(4, 8 * 8 * 6).astype(np.float32)
        fo, fstate = ffwd(fparams.values, fparams.state,
                          {"x": Value(jnp.asarray(xv))}, is_training=True)
        uo, ustate = ufwd(uparams.values, uparams.state,
                          {"x": Value(jnp.asarray(xv))}, is_training=True)
        np.testing.assert_allclose(np.asarray(fo[fname].array),
                                   np.asarray(uo[uname].array),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(fstate["f.mean"]),
                                   np.asarray(ustate["b.mean"]),
                                   rtol=1e-4, atol=1e-5)
        # inference path consistent too
        fo2, _ = ffwd(fparams.values, fstate, {"x": Value(jnp.asarray(xv))},
                      is_training=False)
        uo2, _ = ufwd(uparams.values, ustate, {"x": Value(jnp.asarray(xv))},
                      is_training=False)
        np.testing.assert_allclose(np.asarray(fo2[fname].array),
                                   np.asarray(uo2[uname].array),
                                   rtol=2e-4, atol=2e-4)

    def test_fused_resnet_trains(self, rng):
        """resnet_cifar10 basic blocks with fused_bn — the full model
        trains through the fused op."""
        import paddle_tpu as paddle
        from paddle_tpu import layer
        from paddle_tpu.models import resnet
        from paddle_tpu.topology import Topology, Value
        from paddle_tpu.utils.rng import KeySource
        dt = paddle.data_type

        x = layer.data("img", dt.dense_vector(3 * 8 * 8))
        lbl = layer.data("lbl", dt.integer_value(4))
        c1 = resnet.conv_bn_layer(x, 8, 3, 1, 1, None, ch_in=3,
                                  name="t_c1", fused=True)
        b1 = resnet.basic_block(c1, 8, 8, 1, name="t_b1", fused=True)
        pool = layer.img_pool(b1, pool_size=8, stride=1,
                              pool_type=paddle.pooling.Avg())
        sm = layer.fc(pool, 4, act=paddle.activation.Softmax(), name="sm")
        cost = layer.classification_cost(sm, lbl, name="cost")
        topo = Topology(cost)
        params = paddle.parameters.create(cost, KeySource(0))
        fwd = topo.compile()
        opt = paddle.optimizer.Momentum(momentum=0.9, learning_rate=0.05)
        o = opt.init_state(params.values)
        xv = jnp.asarray(rng.randn(16, 3 * 8 * 8).astype(np.float32))
        yv = jnp.asarray(rng.randint(0, 4, 16).astype(np.int32))

        def step(p, o, s):
            def loss_fn(p):
                outs, ns = fwd(p, s, {"img": Value(xv), "lbl": Value(yv)},
                               is_training=True)
                return jnp.mean(outs["cost"].array.astype(jnp.float32)), ns
            (l, ns), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
            np_, no_ = opt.update(jnp.asarray(0, jnp.int32), g, p, o)
            return l, np_, no_, ns

        p, s = params.values, params.state
        losses = []
        for _ in range(8):
            l, p, o, s = step(p, o, s)
            losses.append(float(l))
        assert losses[-1] < losses[0], losses
        assert np.isfinite(losses).all()

    def test_full_mode_is_retired(self):
        """fused='full' (the deleted Pallas backward kernels) must fail
        loudly with a pointer at the replacement recipes, not silently
        train a different configuration."""
        import paddle_tpu as paddle
        from paddle_tpu import layer
        from paddle_tpu.models import resnet
        dt = paddle.data_type
        x = layer.data("xr", dt.dense_vector(8 * 8 * 3))
        with pytest.raises(ValueError, match="retired"):
            resnet.conv_bn_layer(x, 8, 3, 1, 1, None, ch_in=3,
                                 name="r_c1", fused="full")


class TestFusedUnfusedInterchange:
    """Checkpoint compatibility + stride-2 numerics: the fused and
    unfused conv_bn_layer paths share parameter NAMES and must agree
    numerically for every ResNet conv shape, including the stride-2
    3x3 basic-block transition (asymmetric-SAME regression: the fused
    path must use the same explicit padding as the unfused one)."""

    @pytest.mark.parametrize("ksize,stride,pad", [(3, 2, 1), (3, 1, 1),
                                                  (1, 2, 0), (7, 2, 3)])
    def test_paths_share_names_and_numerics(self, rng, ksize, stride, pad):
        import paddle_tpu as paddle
        from paddle_tpu import layer
        from paddle_tpu.models import resnet
        from paddle_tpu.topology import Topology, Value
        from paddle_tpu.utils.rng import KeySource
        dt = paddle.data_type

        def build(fused_flag):
            x = layer.data("x", dt.dense_vector(8 * 8 * 6))
            out = resnet.conv_bn_layer(
                x, 12, ksize, stride, pad, paddle.activation.Relu(),
                ch_in=6, name="cb", fused=fused_flag)
            topo = Topology(out)
            params = paddle.parameters.create(out, KeySource(11))
            return out.name, topo.compile(), params

        fname, ffwd, fparams = build(True)
        uname, ufwd, uparams = build(False)
        # identical NAMES -> values carry over verbatim (checkpoint
        # interchange between the two paths)
        assert set(fparams.values) == set(uparams.values)
        assert set(fparams.state) == set(uparams.state)
        xv = rng.randn(3, 8 * 8 * 6).astype(np.float32)
        fo, _ = ffwd(uparams.values, uparams.state,
                     {"x": Value(jnp.asarray(xv))}, is_training=True)
        uo, _ = ufwd(uparams.values, uparams.state,
                     {"x": Value(jnp.asarray(xv))}, is_training=True)
        np.testing.assert_allclose(np.asarray(fo[fname].array),
                                   np.asarray(uo[uname].array),
                                   rtol=2e-4, atol=2e-4)


class TestInt8Stash:
    """save8: backward activations stashed per-channel int8 — gradients
    must track the exact path within the ~0.4% stash rounding noise, and
    the forward must be bit-identical (only backward READS change)."""

    def test_forward_identical_grads_close(self, rng):
        n, h, w_, c, k = 2, 6, 6, 8, 16
        # positive-mean inputs + one constant-heavy filter make channel 0
        # mean-dominated (|mean| >> std) — the case raw-y quantization
        # would corrupt through the 1/std amplification; the centered
        # stash must stay accurate here
        x = (np.abs(rng.randn(n, h, w_, c)) + 1.0).astype(np.float32)
        w = rng.randn(3, 3, c, k).astype(np.float32) * 0.2
        w[:, :, :, 0] = 0.5 + rng.randn(3, 3, c) * 0.01
        gamma = rng.rand(k).astype(np.float32) + 0.5
        beta = rng.randn(k).astype(np.float32) * 0.1
        rm = jnp.zeros((k,), jnp.float32)
        rv = jnp.ones((k,), jnp.float32)
        tgt = rng.randn(n, h, w_, k).astype(np.float32)

        def run(save8):
            def loss(x_, w_, g_, b_):
                out, _, _ = fused.conv_bn_train(
                    jnp.asarray(x_), jnp.asarray(w_), jnp.asarray(g_),
                    jnp.asarray(b_), rm, rv, stride=1, save8=save8)
                return jnp.mean((out - tgt) ** 2), out
            (l, out), grads = jax.value_and_grad(
                loss, argnums=(0, 1, 2, 3), has_aux=True)(x, w, gamma,
                                                          beta)
            return out, grads

        out_f, g_f = run(False)
        out_q, g_q = run(True)
        np.testing.assert_array_equal(np.asarray(out_f), np.asarray(out_q))
        for name, a, b in zip("xwgb", g_q, g_f):
            denom = np.abs(np.asarray(b)).max() + 1e-8
            rel = np.abs(np.asarray(a) - np.asarray(b)).max() / denom
            assert rel < 0.03, (name, rel)


def test_fused_honors_compute_dtype_policy(rng):
    """Under the real bf16 MXU policy (conftest forces fp32 for test
    numerics) the fused path must emit the SAME dtype as ops_conv.conv2d
    — a mismatch breaks the custom-VJP cotangent chain in full models
    (regression: benchmarks/fused_bn_quality.py caught fp32 fused output
    meeting a bf16 conv_vjp)."""
    from paddle_tpu.utils.flags import GLOBAL_FLAGS
    old = GLOBAL_FLAGS.get("compute_dtype", "float32")
    GLOBAL_FLAGS.set_if_known("compute_dtype", "bfloat16")
    try:
        x = jnp.asarray(rng.randn(2, 8, 8, 4).astype(np.float32))
        w = jnp.asarray(rng.randn(3, 3, 4, 8).astype(np.float32) * 0.2)
        gamma = jnp.ones((8,), jnp.float32)
        beta = jnp.zeros((8,), jnp.float32)
        rm = jnp.zeros((8,), jnp.float32)
        rv = jnp.ones((8,), jnp.float32)
        out, _, _ = fused.conv_bn_train(x, w, gamma, beta, rm, rv,
                                        stride=1)
        ref = ops_conv.conv2d(x, w, stride=1, padding="SAME")
        assert out.dtype == ref.dtype == jnp.bfloat16

        # and the backward chain composes with a bf16 conv_vjp
        def loss(x_):
            o, _, _ = fused.conv_bn_train(x_, w, gamma, beta, rm, rv,
                                          stride=1, save8=True)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        g = jax.grad(loss)(x)
        assert g.dtype == x.dtype and bool(jnp.isfinite(
            g.astype(jnp.float32)).all())
    finally:
        GLOBAL_FLAGS.set_if_known("compute_dtype", old)


def test_fused_composes_with_dp_sharding(rng):
    """The fused conv+BN custom-VJP op must stay correct when its inputs
    are GSPMD-sharded over the data axis (the multi-chip DP path)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.core import place
    mesh = place.make_mesh((8,), (place.AXIS_DATA,))
    x_host = jnp.asarray(rng.randn(16, 8, 8, 8).astype(np.float32))
    x = jax.device_put(x_host, NamedSharding(
        mesh, P(place.AXIS_DATA, None, None, None)))
    w = jnp.asarray(rng.randn(3, 3, 8, 16).astype(np.float32) * 0.2)
    gamma = jnp.ones((16,), jnp.float32)
    beta = jnp.zeros((16,), jnp.float32)
    rm = jnp.zeros((16,), jnp.float32)
    rv = jnp.ones((16,), jnp.float32)

    @jax.jit
    def step(x, w):
        def loss(w_):
            out, _, _ = fused.conv_bn_train(
                x, w_, gamma, beta, rm, rv, stride=1, save8=True)
            return jnp.mean(out.astype(jnp.float32) ** 2)
        return jax.value_and_grad(loss)(w)

    l_sh, g_sh = step(x, w)
    l_1d, g_1d = step(jax.device_put(x_host, jax.devices()[0]), w)
    np.testing.assert_allclose(float(l_sh), float(l_1d), rtol=1e-6)
    # partitioned f32 reductions reassociate — tolerance, not bit-equal
    np.testing.assert_allclose(np.asarray(g_sh), np.asarray(g_1d),
                               rtol=1e-3, atol=1e-7)
