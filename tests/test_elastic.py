"""Elastic fault tolerance: chaos injection, crash-consistent
checkpoint commits, master epoch fencing, client reconnect backoff, and
the gang supervisor's judgment/restart/shrink machinery
(runtime/supervisor.py — the Go cloud layer's elastic-trainer slot).

The supervisor tests use pure-stdlib subprocess workers (no jax import)
so the whole file stays tier-1 cheap; the full kill-a-trainer chaos
trajectory proofs live in tests/test_elastic_chaos.py (slow lane)."""

import json
import os
import textwrap

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.io import checkpoint as ckpt
from paddle_tpu.runtime import chaos
from paddle_tpu.runtime import supervisor as sup
from paddle_tpu.runtime.master import (DecorrelatedBackoff, MasterClient,
                                       MasterService)


@pytest.fixture(autouse=True)
def _chaos_clean(monkeypatch):
    """Every test starts with a disarmed knob and a clean parse cache."""
    monkeypatch.delenv(chaos.ENV_VAR, raising=False)
    chaos.reset()
    yield
    chaos.reset()


class TestChaosKnob:
    def test_crash_at_named_step_fires_once(self, monkeypatch):
        monkeypatch.setenv(chaos.ENV_VAR, "crash@step:step=3")
        chaos.reset()
        chaos.maybe_trigger("step", step=2)          # no match
        with pytest.raises(chaos.ChaosError):
            chaos.maybe_trigger("step", step=3)
        chaos.maybe_trigger("step", step=3)          # count=1: disarmed

    def test_rank_and_epoch_scope_from_env(self, monkeypatch):
        monkeypatch.setenv(chaos.ENV_VAR, "crash@step:step=1:rank=1:epoch=1")
        monkeypatch.setenv("PADDLE_PROCESS_ID", "1")
        monkeypatch.setenv("PADDLE_ELASTIC_EPOCH", "2")
        chaos.reset()
        chaos.maybe_trigger("step", step=1)     # epoch 2 != 1: survives
        monkeypatch.setenv("PADDLE_ELASTIC_EPOCH", "1")
        with pytest.raises(chaos.ChaosError):
            chaos.maybe_trigger("step", step=1)

    def test_multiple_rules_and_count(self, monkeypatch):
        monkeypatch.setenv(
            chaos.ENV_VAR,
            "crash@checkpoint:phase=pre_commit:count=2,crash@step:step=9")
        chaos.reset()
        for _ in range(2):
            with pytest.raises(chaos.ChaosError):
                chaos.maybe_trigger("checkpoint", phase="pre_commit")
        chaos.maybe_trigger("checkpoint", phase="pre_commit")  # spent
        with pytest.raises(chaos.ChaosError):
            chaos.maybe_trigger("step", step=9)

    def test_action_params_are_not_match_constraints(self, monkeypatch):
        """hang@step:step=2:seconds=0.2 must fire at step 2 — `seconds`
        parameterizes the ACTION; it must not be matched against call
        attrs (which never carry it)."""
        import time
        monkeypatch.setenv(chaos.ENV_VAR, "hang@step:step=2:seconds=0.2")
        chaos.reset()
        t0 = time.perf_counter()
        chaos.maybe_trigger("step", step=2)
        assert time.perf_counter() - t0 >= 0.2   # it actually hung

    def test_malformed_specs_ignored(self, monkeypatch):
        monkeypatch.setenv(chaos.ENV_VAR, "garbage,alsobad@,kill@step:x")
        chaos.reset()
        chaos.maybe_trigger("step", step=1)     # nothing valid armed


class TestCheckpointCrashConsistency:
    """Satellite: interrupt the save between blob write and manifest
    publish; load must fall back to the previous intact step."""

    def _params(self, v=1.0):
        return {"w": jnp.full((4,), v)}

    def test_single_process_crash_pre_manifest_falls_back(
            self, tmp_path, monkeypatch):
        d = str(tmp_path)
        ckpt.save_checkpoint(d, 1, self._params(1.0))
        monkeypatch.setenv(chaos.ENV_VAR,
                           "crash@checkpoint:phase=pre_manifest")
        chaos.reset()
        with pytest.raises(chaos.ChaosError):
            ckpt.save_checkpoint(d, 2, self._params(2.0))
        # previous step intact, no torn dir, no tempdir litter
        latest = ckpt.latest_checkpoint(d)
        assert latest.endswith("ckpt-00000001")
        assert not [f for f in os.listdir(d) if f.startswith(".tmp")]
        step, p, _, _ = ckpt.load_checkpoint(latest, self._params())
        assert step == 1
        np.testing.assert_allclose(np.asarray(p["w"]), 1.0)
        # the retried save (post-restart) succeeds at the same step
        monkeypatch.delenv(chaos.ENV_VAR)
        ckpt.save_checkpoint(d, 2, self._params(2.0))
        assert ckpt.latest_checkpoint(d).endswith("ckpt-00000002")

    def test_multi_host_torn_publish_falls_back(self, tmp_path,
                                                monkeypatch):
        """The manifest-last window: host 1 dies after moving its data
        files but before its manifest — the dir is torn; readers must
        skip it for the previous intact step."""
        d = str(tmp_path)
        for pi in (0, 1):
            ckpt.save_checkpoint(d, 1, self._params(1.0),
                                 process_index=pi, process_count=2)
        assert ckpt.is_complete(os.path.join(d, "ckpt-00000001"))
        ckpt.save_checkpoint(d, 2, self._params(2.0),
                             process_index=0, process_count=2)
        monkeypatch.setenv(chaos.ENV_VAR, "crash@checkpoint:phase=mid_commit")
        chaos.reset()
        with pytest.raises(chaos.ChaosError):
            ckpt.save_checkpoint(d, 2, self._params(2.0),
                                 process_index=1, process_count=2)
        torn = os.path.join(d, "ckpt-00000002")
        assert os.path.isdir(torn) and not ckpt.is_complete(torn)
        with pytest.raises(IOError, match="incomplete"):
            ckpt.load_checkpoint(torn, self._params())
        latest = ckpt.latest_checkpoint(d)
        assert latest.endswith("ckpt-00000001")
        step, p, _, _ = ckpt.load_checkpoint(latest, self._params())
        assert step == 1 and float(np.asarray(p["w"])[0]) == 1.0

    def test_async_checkpointer_crash_surfaces_and_falls_back(
            self, tmp_path, monkeypatch):
        d = str(tmp_path)
        ac = ckpt.AsyncCheckpointer(d)
        ac.save(1, self._params(1.0))
        ac.wait()
        monkeypatch.setenv(chaos.ENV_VAR,
                           "crash@checkpoint:phase=pre_manifest")
        chaos.reset()
        ac.save(2, self._params(2.0))
        with pytest.raises(chaos.ChaosError):
            ac.wait()
        monkeypatch.delenv(chaos.ENV_VAR)
        ac.close()
        assert ckpt.latest_checkpoint(d).endswith("ckpt-00000001")

    def test_prune_budget_counts_complete_only(self, tmp_path,
                                               monkeypatch):
        """Torn dirs must not consume the keep budget (repeated torn
        saves would otherwise evict every restorable checkpoint); old
        torn dirs are deleted, the newest entry is spared (it may be a
        peer's in-flight multi-host save)."""
        d = str(tmp_path)
        for step in (1, 2):
            ckpt.save_checkpoint(d, step, self._params(step), keep=3)
        # a torn dir between the intact ones (host died mid-publish)
        monkeypatch.setenv(chaos.ENV_VAR, "crash@checkpoint:phase=mid_commit")
        chaos.reset()
        with pytest.raises(chaos.ChaosError):
            ckpt.save_checkpoint(d, 3, self._params(3.0),
                                 process_index=0, process_count=2)
        monkeypatch.delenv(chaos.ENV_VAR)
        torn = os.path.join(d, "ckpt-00000003")
        assert os.path.isdir(torn)
        # a RECENT torn dir is spared (a slower peer may still be
        # publishing into it; rmtree must not race its os.replace)...
        ckpt.save_checkpoint(d, 4, self._params(4.0), keep=3)
        names = sorted(x for x in os.listdir(d) if x.startswith("ckpt-"))
        assert names == ["ckpt-00000001", "ckpt-00000002",
                         "ckpt-00000003", "ckpt-00000004"]
        # ...and collected once stale past the grace window
        past = ckpt._TORN_PRUNE_GRACE_S + 60
        os.utime(torn, (os.path.getmtime(torn) - past,
                        os.path.getmtime(torn) - past))
        ckpt.save_checkpoint(d, 5, self._params(5.0), keep=3)
        names = sorted(x for x in os.listdir(d) if x.startswith("ckpt-"))
        # torn step 3 pruned; the 3 newest complete checkpoints survive
        assert names == ["ckpt-00000002", "ckpt-00000004",
                         "ckpt-00000005"]
        assert ckpt.latest_checkpoint(d).endswith("ckpt-00000005")

    def test_resave_after_shrink_converges_torn_dir(self, tmp_path):
        """A dir torn by a 4-process gang (p3 never published) must be
        re-committable by the shrunk 2-process gang: stale p2/p3 pieces
        are dropped so completeness is satisfiable again."""
        d = str(tmp_path)
        for pi in range(3):                    # p0..p2 of 4: torn
            ckpt.save_checkpoint(d, 7, self._params(1.0),
                                 process_index=pi, process_count=4)
        torn = os.path.join(d, "ckpt-00000007")
        assert not ckpt.is_complete(torn)
        for pi in range(2):                    # the shrunk gang re-saves
            ckpt.save_checkpoint(d, 7, self._params(2.0),
                                 process_index=pi, process_count=2)
        assert ckpt.is_complete(torn)
        assert not [f for f in os.listdir(torn) if ".p2." in f
                    or ".p3." in f]
        step, p, _, _ = ckpt.load_checkpoint(torn, self._params())
        assert step == 7 and float(np.asarray(p["w"])[0]) == 2.0

    def test_same_step_resave_replaces_committed_dir(self, tmp_path):
        """Re-saving an existing step (restore + re-executed window)
        replaces the dir via rename-aside — new content wins, no
        .tmp/.old litter survives."""
        d = str(tmp_path)
        ckpt.save_checkpoint(d, 3, self._params(1.0))
        ckpt.save_checkpoint(d, 3, self._params(2.0))
        step, p, _, _ = ckpt.load_checkpoint(
            ckpt.latest_checkpoint(d), self._params())
        assert step == 3 and float(np.asarray(p["w"])[0]) == 2.0
        assert not [f for f in os.listdir(d) if f.startswith(".tmp")]

    def test_mixed_incarnation_pieces_judged_incomplete(
            self, tmp_path, monkeypatch):
        """A same-size re-save into a torn dir can transiently hold
        old-epoch and new-epoch pieces that cover every process index;
        the save_epoch stamp must keep that mix from loading as a
        complete checkpoint (no cross-incarnation shard merges)."""
        d = str(tmp_path)
        ckpt.save_checkpoint(d, 1, self._params(1.0))   # intact fallback
        # incarnation 1: only p1 of 2 published before the gang died
        monkeypatch.setenv("PADDLE_ELASTIC_EPOCH", "1")
        ckpt.save_checkpoint(d, 2, self._params(1.0),
                             process_index=1, process_count=2)
        # incarnation 2: p0 published, p1 not yet — indices {0,1} are
        # now covered but by two different save attempts
        monkeypatch.setenv("PADDLE_ELASTIC_EPOCH", "2")
        ckpt.save_checkpoint(d, 2, self._params(2.0),
                             process_index=0, process_count=2)
        torn = os.path.join(d, "ckpt-00000002")
        assert not ckpt.is_complete(torn)
        with pytest.raises(IOError, match="mixed save incarnations"):
            ckpt.load_checkpoint(torn, self._params())
        assert ckpt.latest_checkpoint(d).endswith("ckpt-00000001")
        # incarnation 2 finishes: p1's replace overwrites the stale
        # piece and the dir converges to one complete incarnation
        ckpt.save_checkpoint(d, 2, self._params(2.0),
                             process_index=1, process_count=2)
        assert ckpt.is_complete(torn)
        step, p, _, _ = ckpt.load_checkpoint(torn, self._params())
        assert step == 2 and float(np.asarray(p["w"])[0]) == 2.0

    def test_fence_rejects_commit(self, tmp_path):
        d = str(tmp_path)
        ckpt.save_checkpoint(d, 1, self._params())
        with pytest.raises(ckpt.CheckpointFencedError):
            ckpt.save_checkpoint(d, 2, self._params(),
                                 fence=lambda: False)
        assert ckpt.latest_checkpoint(d).endswith("ckpt-00000001")
        assert not [f for f in os.listdir(d) if f.startswith(".tmp")]

    def test_async_fence_surfaces(self, tmp_path):
        ac = ckpt.AsyncCheckpointer(str(tmp_path), fence=lambda: False)
        ac.save(1, self._params())
        with pytest.raises(ckpt.CheckpointFencedError):
            ac.wait()
        ac.close()
        assert ckpt.latest_checkpoint(str(tmp_path)) is None


class TestElasticEpochFence:
    """A zombie from a torn-down gang can commit NOTHING: checkpoints
    abort on the env fence, task RPCs bounce off the master fence."""

    def test_env_fence_follows_epoch_file(self, tmp_path, monkeypatch):
        sd = str(tmp_path)
        sup.write_epoch(sd, 1)
        monkeypatch.setenv(sup.ENV_DIR, sd)
        monkeypatch.setenv(sup.ENV_EPOCH, "1")
        fence = sup.fence_from_env()
        assert fence()                      # current incarnation
        sup.write_epoch(sd, 2)              # the supervisor moved on
        assert not fence()                  # zombie now
        with pytest.raises(ckpt.CheckpointFencedError):
            ckpt.save_checkpoint(str(tmp_path / "ck"), 5,
                                 {"w": jnp.ones(2)}, fence=fence)

    def test_fence_none_outside_supervisor(self, monkeypatch):
        monkeypatch.delenv(sup.ENV_DIR, raising=False)
        monkeypatch.delenv(sup.ENV_EPOCH, raising=False)
        assert sup.fence_from_env() is None

    def test_master_rejects_zombie_task_rpcs(self, tmp_path):
        from paddle_tpu.runtime import recordio
        path = str(tmp_path / "d.rio")
        with recordio.Writer(path, records_per_chunk=4) as w:
            for i in range(8):
                w.write(b"x%d" % i)
        svc = MasterService()
        svc.set_dataset([path])
        zombie = MasterClient(service=svc, worker_epoch=1)
        live = MasterClient(service=svc, worker_epoch=2)
        t = zombie.get_task()
        assert t is not None                # pre-fence: all is well
        svc.set_epoch_fence(2)              # gang restarted as epoch 2
        assert zombie.get_task() is None
        zombie.report_done(t.task_id, t.lease)   # silently rejected
        assert svc.num_pending() == 1       # the lease did NOT commit
        t2 = live.get_task()
        assert t2 is not None               # the live gang still leases
        # the save-model election is fenced the same way: a zombie must
        # not grab the grant and starve the live gang's save windows
        assert not zombie.request_save_model("zombie-0")
        assert live.request_save_model("live-0")

    def test_fence_survives_snapshot_failover(self, tmp_path):
        from paddle_tpu.runtime import recordio
        path = str(tmp_path / "d.rio")
        with recordio.Writer(path, records_per_chunk=4) as w:
            for i in range(8):
                w.write(b"y%d" % i)
        snap = str(tmp_path / "m.snap")
        svc = MasterService(snapshot_path=snap)
        svc.set_dataset([path])
        svc.set_epoch_fence(3)
        svc.snapshot()
        svc.close()
        svc2 = MasterService(snapshot_path=snap)
        assert svc2._epoch_fence == 3
        # the restored fence actually REJECTS: stale epoch gets no
        # task while a current-epoch worker leases normally
        assert svc2.get_task(worker_epoch=2) is None
        assert svc2.get_task(worker_epoch=3) is not None
        svc2.close()


class TestClientBackoff:
    def test_decorrelated_jitter_bounds_and_cap(self):
        import random
        b = DecorrelatedBackoff(base=0.1, cap=1.0,
                                rng=random.Random(7))
        seq = [b.next() for _ in range(64)]
        assert all(0.1 <= s <= 1.0 for s in seq)
        assert max(seq) > 0.5               # it does grow toward the cap
        b.reset()
        assert b.next() <= 0.3              # reset restarts the ramp

    def test_client_retries_with_backoff_then_raises(self, tmp_path,
                                                     monkeypatch):
        """A dead discovery path: the client must retry with growing,
        jittered sleeps (not a fixed cadence) and give up at the
        failover deadline."""
        sleeps = []
        monkeypatch.setattr("time.sleep", lambda s: sleeps.append(s))
        lock = str(tmp_path / "no.lock")
        os.makedirs(lock)
        with open(os.path.join(lock, "info.json"), "w") as f:
            json.dump({"host": "127.0.0.1", "port": 1, "term": 1}, f)
        c = MasterClient(discovery_path=lock, failover_timeout=0.5,
                         connect_timeout=0.1, backoff_base=0.05,
                         backoff_cap=0.4)
        with pytest.raises((ConnectionError, OSError)):
            c.status()
        assert len(sleeps) >= 2
        assert all(0.05 <= s <= 0.4 for s in sleeps)
        assert len(set(round(s, 6) for s in sleeps)) > 1  # jittered


def _write_worker(tmp_path, body):
    """A pure-stdlib gang worker (fast: no jax import). ``body`` runs
    with helpers: rank, epoch, nprocs, beat(step[, wedge]), finish()."""
    w = tmp_path / "worker.py"
    w.write_text(textwrap.dedent("""
        import json, os, signal, sys, time
        sd = os.environ["PADDLE_ELASTIC_DIR"]
        rank = int(os.environ["PADDLE_PROCESS_ID"])
        nprocs = int(os.environ["PADDLE_NUM_PROCESSES"])
        epoch = int(os.environ["PADDLE_ELASTIC_EPOCH"])
        hbd = os.path.join(sd, "hb"); os.makedirs(hbd, exist_ok=True)
        _p = os.path.join(hbd, "worker_%d.json" % rank)
        _step_ts = [time.time()]
        def _write(extra):
            rec = {"rank": rank, "pid": os.getpid(), "epoch": epoch,
                   "ts": time.time()}
            rec.update(extra)
            json.dump(rec, open(_p + ".t", "w"))
            os.replace(_p + ".t", _p)
        def beat(step, wedge=False):
            if not wedge:
                _step_ts[0] = time.time()
            _write({"step": step, "step_ts": _step_ts[0]})
        def finish():
            _write({"done": True})
    """) + textwrap.dedent(body))
    return str(w)


def _mk_sup(worker, tmp_path, nprocs, **kw):
    kw.setdefault("heartbeat_window", 3.0)
    kw.setdefault("startup_grace", 20.0)
    kw.setdefault("poll_interval", 0.05)
    kw.setdefault("backoff_base", 0.05)
    kw.setdefault("backoff_cap", 0.2)
    return sup.Supervisor([worker], nprocs=nprocs,
                          state_dir=str(tmp_path / "state"), **kw)


class TestSupervisor:
    def test_killed_worker_detected_and_gang_restarted(self, tmp_path):
        worker = _write_worker(tmp_path, """
            for step in range(8):
                beat(step)
                if rank == 1 and epoch == 1 and step == 3:
                    os.kill(os.getpid(), signal.SIGKILL)
                time.sleep(0.03)
            finish()
        """)
        s = _mk_sup(worker, tmp_path, nprocs=2, max_restarts=3)
        res = s.run(total_timeout=60)
        assert res["ok"] and res["restarts"] == 1
        assert res["epoch"] == 2
        assert res["attempts"][0]["reason"].startswith("worker_exit")
        assert res["attempts"][0]["failed_ranks"] == [1]
        # recovery (detect -> first post-restore step) was measured
        assert res["attempts"][1]["recovery_seconds"] > 0
        # the restart left a flight-recorder post-mortem
        flights = os.listdir(tmp_path / "state" / "flight")
        assert flights == ["restart_epoch0001.json"]
        doc = json.load(open(tmp_path / "state" / "flight" / flights[0]))
        assert doc["reason"].startswith("gang restart")

    def test_wedged_worker_detected_by_step_stall(self, tmp_path):
        worker = _write_worker(tmp_path, """
            for step in range(40):
                # epoch 1 rank 0 stalls step progress from step 2 on
                # while keeping the liveness file fresh — the wedge
                beat(min(step, 2) if (rank == 0 and epoch == 1) else step,
                     wedge=(rank == 0 and epoch == 1 and step >= 2))
                time.sleep(0.05)
                if step >= 6 and not (rank == 0 and epoch == 1):
                    break
            finish()
        """)
        s = _mk_sup(worker, tmp_path, nprocs=2, max_restarts=2,
                    wedge_window=0.6)
        res = s.run(total_timeout=60)
        assert res["ok"] and res["restarts"] == 1
        assert res["attempts"][0]["reason"] == "wedged"
        assert res["attempts"][0]["failed_ranks"] == [0]

    def test_shrink_when_no_replacement(self, tmp_path):
        """Graceful degradation: a dead rank with no spare host shrinks
        the gang (snapped to a valid mesh size) instead of killing the
        run — the 4->2 resize semantics, light edition."""
        worker = _write_worker(tmp_path, """
            if rank >= 2:
                sys.exit(3)          # this "host" is simply gone
            for step in range(5):
                beat(step); time.sleep(0.02)
            finish()
        """)
        s = _mk_sup(worker, tmp_path, nprocs=4, max_restarts=2,
                    replacements=0, valid_sizes=[4, 2, 1])
        res = s.run(total_timeout=60)
        assert res["ok"], res
        assert res["restarts"] == 1
        assert res["attempts"][1]["nprocs"] == 2   # 4 -> 2 (snapped)
        assert s.nprocs == 2

    def test_stable_incarnation_refills_restart_budget(self, tmp_path):
        """max_restarts guards crash LOOPS: an incarnation that stepped
        and survived stable_window refills the budget when it fails, so
        three independent 'preemptions' pass under max_restarts=1."""
        worker = _write_worker(tmp_path, """
            for step in range(30):
                beat(step)
                time.sleep(0.03)
                if step == 12 and epoch < 4:
                    sys.exit(1)      # dies AFTER running stably
            finish()
        """)
        s = _mk_sup(worker, tmp_path, nprocs=1, max_restarts=1,
                    stable_window=0.2)
        res = s.run(total_timeout=60)
        assert res["ok"], res
        assert res["restarts"] == 1          # counter kept resetting
        assert res["epoch"] == 4             # three failures survived

    def test_attempt_timeout_retries_same_gang_size(self, tmp_path):
        """A whole-gang timeout names no dead machine: the retry keeps
        the gang size (no host drop, no replacement debit)."""
        worker = _write_worker(tmp_path, """
            if epoch == 1:
                for step in range(200):
                    beat(step); time.sleep(0.05)   # too slow: times out
            for step in range(3):
                beat(step); time.sleep(0.02)
            finish()
        """)
        s = _mk_sup(worker, tmp_path, nprocs=2, max_restarts=2,
                    replacements=0, attempt_timeout=1.0)
        res = s.run(total_timeout=60)
        assert res["ok"], res
        assert res["attempts"][0]["reason"] == "attempt_timeout"
        assert res["attempts"][1]["nprocs"] == 2   # gang NOT shrunk
        assert s.nprocs == 2

    def test_gives_up_after_max_restarts(self, tmp_path):
        worker = _write_worker(tmp_path, "sys.exit(1)\n")
        s = _mk_sup(worker, tmp_path, nprocs=1, max_restarts=1,
                    startup_grace=5.0)
        res = s.run(total_timeout=30)
        assert not res["ok"] and res["reason"] == "max_restarts"
        assert res["restarts"] == 2            # initial + 1 retry

    def test_epoch_is_monotonic_across_supervisors(self, tmp_path):
        worker = _write_worker(tmp_path, "finish()\n")
        s1 = _mk_sup(worker, tmp_path, nprocs=1)
        assert s1.run(total_timeout=30)["epoch"] == 1
        s2 = _mk_sup(worker, tmp_path, nprocs=1)
        assert s2.run(total_timeout=30)["epoch"] == 2
        assert sup.current_epoch(str(tmp_path / "state")) == 2

    def test_master_fence_bumped_on_restart(self, tmp_path):
        worker = _write_worker(tmp_path, """
            if epoch == 1:
                sys.exit(1)
            finish()
        """)
        svc = MasterService()
        s = _mk_sup(worker, tmp_path, nprocs=1, max_restarts=2,
                    master=svc, startup_grace=5.0)
        res = s.run(total_timeout=30)
        assert res["ok"] and res["restarts"] == 1
        # the fence followed the gang to epoch 2: epoch-1 zombies are out
        assert svc._epoch_fence == 2
        assert svc.get_task(worker_epoch=1) is None

    def test_ssh_mode_replacement_host_injection(self, tmp_path):
        """A dead host is swapped for a spare before relaunch (ssh mode
        through the local fakessh shim used by TestSshLaunch)."""
        shim = tmp_path / "fakessh"
        shim.write_text("#!/bin/bash\nshift\nexec bash -c \"$*\"\n")
        shim.chmod(0o755)
        worker = tmp_path / "w.py"
        worker.write_text(textwrap.dedent("""
            import os, sys
            if os.environ["PADDLE_GANG_HOST"] == "hB":
                sys.exit(7)          # hB is a bad machine
        """))
        s = sup.Supervisor(
            ["python", str(worker)], nprocs=0,
            state_dir=str(tmp_path / "state"),
            hosts=["hA", "hB"], replacement_hosts=["hC"],
            ssh_cmd=(str(shim),), startup_grace=20.0,
            poll_interval=0.05, backoff_base=0.05, backoff_cap=0.2,
            max_restarts=2)
        res = s.run(total_timeout=60)
        assert res["ok"] and res["restarts"] == 1
        assert s.hosts == ["hA", "hC"]

    def test_health_doc(self, tmp_path):
        worker = _write_worker(tmp_path, "finish()\n")
        s = _mk_sup(worker, tmp_path, nprocs=1)
        res = s.run(total_timeout=30)
        assert res["ok"]
        doc = s.health()
        assert doc["state"] == "done" and doc["healthy"]
        assert doc["workers"]["0"]["done"]
