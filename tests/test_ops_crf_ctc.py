"""CRF + CTC dynamic programs vs brute-force references.

Mirrors the reference's strategy for these ops: linear_chain_crf_op is tested
against a per-sequence numpy DP (test_linear_chain_crf_op.py) and CTC against
path enumeration (gserver/tests/test_LinearChainCRF.cpp, test_WarpCTCLayer).
Here tiny cases are checked by *exhaustive path enumeration* in float64 —
stronger than a second DP — plus jax.grad vs numeric gradients.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import crf as ops_crf
from paddle_tpu.ops import ctc as ops_ctc
from op_test_util import check_grad


def brute_crf(emis, tags, length, w):
    """Path score and logZ by enumeration. emis [T, N], w [(N+2), N]."""
    start, end, trans = w[0], w[1], w[2:]
    N = emis.shape[1]

    def score(path):
        s = start[path[0]] + end[path[length - 1]]
        for t in range(length):
            s += emis[t, path[t]]
        for t in range(1, length):
            s += trans[path[t - 1], path[t]]
        return s

    all_scores = [score(p) for p in itertools.product(range(N), repeat=length)]
    logz = np.logaddexp.reduce(np.array(all_scores, np.float64))
    return score(tags[:length]), logz


class TestCRF:
    def setup_method(self, _):
        rng = np.random.RandomState(7)
        self.B, self.T, self.N = 3, 4, 3
        self.emis = rng.randn(self.B, self.T, self.N).astype(np.float64)
        self.w = (0.5 * rng.randn(self.N + 2, self.N)).astype(np.float64)
        self.lengths = np.array([4, 2, 3], np.int32)
        self.tags = rng.randint(0, self.N, (self.B, self.T)).astype(np.int32)

    def test_log_likelihood_vs_enumeration(self):
        got = np.asarray(ops_crf.crf_log_likelihood(
            jnp.asarray(self.emis, jnp.float32), jnp.asarray(self.tags),
            jnp.asarray(self.lengths), jnp.asarray(self.w, jnp.float32)))
        for b in range(self.B):
            sc, logz = brute_crf(self.emis[b], self.tags[b],
                                 int(self.lengths[b]), self.w)
            np.testing.assert_allclose(got[b], sc - logz, rtol=1e-4, atol=1e-4)

    def test_decode_vs_enumeration(self):
        tags, score = ops_crf.crf_decode(
            jnp.asarray(self.emis, jnp.float32), jnp.asarray(self.lengths),
            jnp.asarray(self.w, jnp.float32))
        tags, score = np.asarray(tags), np.asarray(score)
        for b in range(self.B):
            L, N = int(self.lengths[b]), self.N
            best, best_p = -1e30, None
            for p in itertools.product(range(N), repeat=L):
                s, _ = brute_crf(self.emis[b], list(p), L, self.w)
                if s > best:
                    best, best_p = s, p
            assert tuple(tags[b, :L]) == best_p
            np.testing.assert_allclose(score[b], best, rtol=1e-4, atol=1e-4)

    def test_grads(self):
        lengths, tags = jnp.asarray(self.lengths), jnp.asarray(self.tags)

        def nll_wrt_emis(emis, w):
            return -ops_crf.crf_log_likelihood(emis, tags, lengths, w)

        check_grad(nll_wrt_emis, [self.emis.astype(np.float32),
                                  self.w.astype(np.float32)], wrt=0)
        check_grad(nll_wrt_emis, [self.emis.astype(np.float32),
                                  self.w.astype(np.float32)], wrt=1)

    def test_jit_and_padding_invariance(self):
        # padded tail values must not affect results
        e2 = self.emis.copy()
        e2[1, 2:] = 999.0  # sequence 1 has length 2
        f = jax.jit(ops_crf.crf_log_likelihood)
        a = f(jnp.asarray(self.emis, jnp.float32), jnp.asarray(self.tags),
              jnp.asarray(self.lengths), jnp.asarray(self.w, jnp.float32))
        b = f(jnp.asarray(e2, jnp.float32), jnp.asarray(self.tags),
              jnp.asarray(self.lengths), jnp.asarray(self.w, jnp.float32))
        np.testing.assert_allclose(a[1], b[1], rtol=1e-5)


def brute_ctc(logp, label, T):
    """-log p(label) by enumerating all T-length alignment paths."""
    C = logp.shape[1]
    total = -np.inf
    for path in itertools.product(range(C), repeat=T):
        # collapse: remove repeats then blanks (blank=0)
        collapsed = []
        prev = -1
        for c in path:
            if c != prev and c != 0:
                collapsed.append(c)
            prev = c
        if collapsed == list(label):
            total = np.logaddexp(total, sum(logp[t, path[t]]
                                            for t in range(T)))
    return -total


class TestCTC:
    def _logp(self, rng, B, T, C):
        x = rng.randn(B, T, C).astype(np.float64)
        return x - np.log(np.sum(np.exp(x), -1, keepdims=True))

    def test_vs_enumeration(self):
        rng = np.random.RandomState(3)
        B, T, C, L = 3, 4, 3, 2
        logp = self._logp(rng, B, T, C)
        labels = np.array([[1, 2], [2, 2], [1, 0]], np.int32)
        lab_len = np.array([2, 2, 1], np.int32)
        in_len = np.array([4, 4, 3], np.int32)
        got = np.asarray(ops_ctc.ctc_loss(
            jnp.asarray(logp, jnp.float32), jnp.asarray(labels),
            jnp.asarray(in_len), jnp.asarray(lab_len)))
        for b in range(B):
            want = brute_ctc(logp[b, :in_len[b]],
                             list(labels[b, :lab_len[b]]), int(in_len[b]))
            np.testing.assert_allclose(got[b], want, rtol=1e-4, atol=1e-4)

    def test_empty_label(self):
        rng = np.random.RandomState(4)
        logp = self._logp(rng, 1, 3, 3)
        got = float(ops_ctc.ctc_loss(jnp.asarray(logp, jnp.float32),
                                     jnp.zeros((1, 2), jnp.int32),
                                     jnp.array([3]), jnp.array([0]))[0])
        want = -float(logp[0, 0, 0] + logp[0, 1, 0] + logp[0, 2, 0])
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_grad(self):
        rng = np.random.RandomState(5)
        B, T, C = 2, 4, 3
        x = rng.randn(B, T, C).astype(np.float32)
        labels = jnp.asarray(np.array([[1, 2], [2, 1]], np.int32))
        in_len, lab_len = jnp.array([4, 3]), jnp.array([2, 2])

        def loss(logits):
            logp = jax.nn.log_softmax(logits, axis=-1)
            return ops_ctc.ctc_loss(logp, labels, in_len, lab_len)

        check_grad(loss, [x], wrt=0)

    def test_greedy_decode(self):
        # frames argmax: [1,1,0,2] -> collapse -> [1,2]
        logp = np.full((1, 4, 3), -5.0, np.float32)
        for t, c in enumerate([1, 1, 0, 2]):
            logp[0, t, c] = 0.0
        out, n = ops_ctc.ctc_greedy_decode(jnp.asarray(logp), jnp.array([4]))
        assert int(n[0]) == 2
        assert list(np.asarray(out[0, :2])) == [1, 2]


class TestCRFLayers:
    def test_crf_train_and_decode_layers(self):
        import paddle_tpu as paddle
        from paddle_tpu import layer
        from paddle_tpu.topology import Topology, Value
        from paddle_tpu.utils.rng import KeySource

        T, N = 5, 4
        feat = layer.data("feat", paddle.data_type.dense_vector_sequence(8))
        lab = layer.data("lab", paddle.data_type.integer_value_sequence(N))
        emis = layer.fc(feat, size=N, act="linear", name="emis")
        cost = layer.crf_layer(emis, lab, name="crf",
                               param_attr=paddle.attr.Param(name="crfw"))
        dec = layer.crf_decoding_layer(
            emis, size=N, param_attr=paddle.attr.Param(name="crfw"),
            name="dec")
        topo = Topology([cost, dec])
        params = paddle.parameters.create([cost, dec], KeySource(0))
        fwd = topo.compile()
        rng = np.random.RandomState(0)
        B = 3
        x = jnp.asarray(rng.randn(B, T, 8).astype(np.float32))
        lens = jnp.asarray(np.array([5, 3, 4], np.int32))
        y = jnp.asarray(rng.randint(0, N, (B, T)).astype(np.int32))
        outs, _ = fwd(params.values, params.state,
                      {"feat": Value(x, lengths=lens),
                       "lab": Value(y, lengths=lens)})
        assert outs["crf"].array.shape == (B,)
        assert np.all(np.asarray(outs["crf"].array) > 0)  # NLL positive
        assert outs["dec"].array.shape == (B, T)
