"""Dataset modules: schema contracts, provenance labelling, split streaming,
and the real-file parsers where a fixture can be synthesised on the fly
(reference test strategy: python/paddle/v2/dataset/tests/*)."""

import gzip
import os
import struct
import tarfile

import numpy as np
import pytest

from paddle_tpu.dataset import (cifar, common, conll05, flowers, imdb,
                                imikolov, mnist, movielens, mq2007,
                                sentiment, synthetic, uci_housing, voc2012,
                                wmt14)


def take(reader_fn, n):
    out = []
    for sample in reader_fn():
        out.append(sample)
        if len(out) >= n:
            break
    return out


class TestProvenance:
    def test_synthetic_fallbacks_are_labelled(self):
        for reader in (mnist.train(), cifar.train10(), uci_housing.train(),
                       imdb.train(), movielens.train(), conll05.train(),
                       wmt14.train(), sentiment.train(), voc2012.train(),
                       flowers.train(), mq2007.train()):
            assert getattr(reader, "provenance", None) in (
                "synthetic", "real")

    def test_real_data_marks(self, tmp_path):
        # fabricate a tiny idx-format MNIST cache and check provenance flips
        old = common.DATA_HOME
        common.DATA_HOME = str(tmp_path)
        try:
            d = tmp_path / "mnist"
            d.mkdir()
            imgs = np.random.RandomState(0).randint(
                0, 255, (4, 28, 28), np.uint8)
            labs = np.arange(4, dtype=np.uint8)
            with gzip.open(d / mnist.TRAIN_IMAGES, "wb") as f:
                f.write(struct.pack(">IIII", 2051, 4, 28, 28))
                f.write(imgs.tobytes())
            with gzip.open(d / mnist.TRAIN_LABELS, "wb") as f:
                f.write(struct.pack(">II", 2049, 4))
                f.write(labs.tobytes())
            r = mnist.train()
            assert r.provenance == "real"
            samples = take(r, 4)
            assert len(samples) == 4
            assert samples[0][0].shape == (784,)
            assert [s[1] for s in samples] == [0, 1, 2, 3]
        finally:
            common.DATA_HOME = old


class TestSchemas:
    def test_movielens_schema(self):
        s = take(movielens.train(), 3)[0]
        uid, gender, age, job, mid, cats, title, rating = s
        assert gender in (0, 1)
        assert 0 <= age < len(movielens.age_table)
        assert isinstance(cats, list) and isinstance(title, list)
        assert isinstance(rating, list) and len(rating) == 1
        assert -5.0 <= rating[0] <= 5.0
        assert movielens.max_user_id() >= uid
        assert movielens.max_movie_id() >= mid
        assert movielens.max_job_id() >= job

    def test_conll05_schema(self):
        word_d, verb_d, label_d = conll05.get_dict()
        s = take(conll05.train(), 2)[0]
        assert len(s) == 9
        n = len(s[0])
        for feat in s:
            assert len(feat) == n
        # ctx features are constant across the sentence
        assert len(set(s[1])) == 1 and len(set(s[6])) == 1
        assert set(s[7]) <= {0, 1}
        assert all(0 <= t < len(label_d) for t in s[8])

    def test_wmt14_schema(self):
        src, trg, trg_next = take(wmt14.train(dict_size=1000), 2)[0]
        assert trg[0] == 0                      # <s>
        assert trg_next[-1] == 1                # <e>
        assert trg[1:] == trg_next[:-1]

    def test_sentiment_schema(self):
        toks, lbl = take(sentiment.train(), 2)[0]
        assert lbl in (0, 1) and all(isinstance(t, (int, np.integer))
                                     for t in toks)

    def test_voc2012_schema(self):
        img, mask = take(voc2012.train(), 1)[0]
        assert img.ndim == 3 and img.shape[2] == 3 and img.dtype == np.uint8
        assert mask.shape == img.shape[:2]
        assert mask.max() < voc2012.NUM_CLASSES

    def test_flowers_schema(self):
        x, y = take(flowers.train(), 1)[0]
        assert x.shape == (flowers.IMG_DIM,)
        assert 0 <= y < 102

    def test_mq2007_formats(self):
        lbl, better, worse = take(mq2007.train("pairwise"), 1)[0]
        assert better.shape == (mq2007.FEATURE_DIM,)
        s, v = take(mq2007.train("listwise"), 1)[0]
        assert v.shape == (len(s), mq2007.FEATURE_DIM)
        score, vec = take(mq2007.train("pointwise"), 1)[0]
        assert vec.shape == (mq2007.FEATURE_DIM,)

    def test_imikolov_seq_fallback_schema(self):
        src, trg = take(imikolov.train(n=0, data_type=imikolov.DataType.SEQ),
                        2)[0]
        assert src[1:] == trg[:-1]

    def test_imdb_word_dict_has_unk(self):
        d = imdb.build_dict()
        assert "<unk>" in d


class TestRealParsers:
    def test_wmt14_tar_roundtrip(self, tmp_path):
        old = common.DATA_HOME
        common.DATA_HOME = str(tmp_path)
        try:
            d = tmp_path / "wmt14"
            d.mkdir()
            root = tmp_path / "build"
            (root / "train").mkdir(parents=True)
            (root / "test").mkdir()
            words = ["le", "chat", "sits", "the", "cat", "sat"]
            (root / "src.dict").write_text(
                "\n".join(["<s>", "<e>", "<unk>"] + words) + "\n")
            (root / "trg.dict").write_text(
                "\n".join(["<s>", "<e>", "<unk>"] + words) + "\n")
            (root / "train" / "train").write_text(
                "le chat\tthe cat\nle chat sits\tthe cat sat\n")
            (root / "test" / "test").write_text("le\tthe\n")
            with tarfile.open(d / wmt14.ARCHIVE, "w:gz") as tf:
                for p in root.rglob("*"):
                    if p.is_file():
                        tf.add(p, arcname=str(p.relative_to(root)))
            wmt14._dict_cache.clear()
            r = wmt14.train(dict_size=100)
            assert r.provenance == "real"
            samples = list(r())
            assert len(samples) == 2
            src, trg, trg_next = samples[0]
            assert src[0] == 0 and src[-1] == 1       # <s> ... <e>
            assert trg_next[-1] == 1
        finally:
            wmt14._dict_cache.clear()
            common.DATA_HOME = old

    def test_mq2007_letor_parser(self, tmp_path):
        fold = tmp_path / "mq2007" / "MQ2007" / "Fold1"
        fold.mkdir(parents=True)
        lines = []
        for qid, rels in ((10, [2, 0]), (11, [1, 1, 0])):
            for r in rels:
                feats = " ".join(f"{k}:{0.1*k}" for k in range(1, 47))
                lines.append(f"{r} qid:{qid} {feats} #docid x")
        (fold / "train.txt").write_text("\n".join(lines) + "\n")
        old = common.DATA_HOME
        common.DATA_HOME = str(tmp_path)
        try:
            r = mq2007.train("listwise")
            assert r.provenance == "real"
            qs = list(r())
            assert len(qs) == 2
            assert len(qs[0][0]) == 2 and len(qs[1][0]) == 3
            # pairwise emits only score-ordered pairs
            pairs = list(mq2007.train("pairwise")())
            assert len(pairs) == 1 + 2       # (2>0), (1>0)x2
        finally:
            common.DATA_HOME = old


class TestCommonHelpers:
    def test_split_streams(self):
        chunks = list(common.split(lambda: iter(range(10)), 4))
        assert chunks == [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]

    def test_split_to_recordio_requires_slot(self, tmp_path):
        with pytest.raises(ValueError, match="slot"):
            common.split_to_recordio(lambda: iter(range(4)),
                                     str(tmp_path / "out.rio"))

    def test_split_to_recordio(self, tmp_path):
        from paddle_tpu.runtime import recordio
        paths = common.split_to_recordio(
            lambda: iter(range(10)), str(tmp_path / "c-%d.rio"),
            line_count=4)
        assert len(paths) == 3
        got = [r for p in paths for r in recordio.read_records(p)]
        assert got == list(range(10))


class TestTripwires:
    def test_check_numerics_catches_bf16_nan(self):
        import jax.numpy as jnp

        from paddle_tpu.utils import enforce
        bad = {"w": jnp.asarray([1.0, float("nan")], jnp.bfloat16)}
        with pytest.raises(enforce.EnforceError, match="NaN"):
            enforce.check_numerics(bad, "param")
        enforce.check_numerics({"w": jnp.ones(3, jnp.bfloat16)})

    def test_init_debug_nans_sets_jax_config(self):
        import jax

        import paddle_tpu as paddle
        from paddle_tpu.utils.flags import GLOBAL_FLAGS
        try:
            paddle.init(debug_nans=True)
            assert jax.config.jax_debug_nans
        finally:
            jax.config.update("jax_debug_nans", False)
            GLOBAL_FLAGS.set("debug_nans", False)

    def test_trainer_raises_on_nan_cost(self, tmp_path, monkeypatch):
        import paddle_tpu as paddle
        from paddle_tpu import layer
        from paddle_tpu.utils import enforce
        from paddle_tpu.utils.flags import GLOBAL_FLAGS
        from paddle_tpu.utils.rng import KeySource

        # the tripwire now also dumps a flight-recorder post-mortem —
        # keep it out of the working directory
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))

        x = layer.data("x", paddle.data_type.dense_vector(4))
        lbl = layer.data("lbl", paddle.data_type.integer_value(2))
        out = layer.fc(x, 2, act=paddle.activation.Softmax(), name="tw_out")
        cost = layer.classification_cost(out, lbl, name="tw_cost")
        params = paddle.parameters.create(cost, KeySource(0))
        tr = paddle.trainer.SGD(cost=cost, parameters=params,
                                update_equation=paddle.optimizer.Momentum(
                                    learning_rate=0.1))

        def reader():
            yield [np.array([np.inf, 1, 1, 1], np.float32), 0]

        GLOBAL_FLAGS.set("debug_infs", True)
        try:
            with pytest.raises(enforce.EnforceError, match="non-finite"):
                tr.train(reader=paddle.batch(reader, 1), num_passes=1)
        finally:
            GLOBAL_FLAGS.set("debug_infs", False)
        assert list(tmp_path.glob("flight_*.json"))   # post-mortem left
