"""recurrent_group / memory / beam_search — the RecurrentGradientMachine
equivalent.

Test strategy mirrors the reference's config-equivalence goldens
(gserver/tests/test_RecurrentGradientMachine.cpp compared recurrent_group
networks against their fused-layer twins) plus generation checks
(test_recurrent_machine_generation.cpp).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu.ops import beam as ops_beam
from paddle_tpu.topology import Topology, Value
from paddle_tpu.utils.rng import KeySource


def _feed(x, lens=None):
    return Value(jnp.asarray(x), None if lens is None else jnp.asarray(lens))


class TestRecurrentGroup:
    def test_matches_fused_recurrent_layer(self, rng):
        """A hand-built rnn step via recurrent_group must equal the fused
        layer.recurrent (the reference's sequence_rnn vs recurrent_layer
        golden pair, gserver/tests/sequence_rnn.conf)."""
        B, T, F, H = 3, 5, 4, 6
        x = layer.data("x", paddle.data_type.dense_vector_sequence(F))

        def step(x_t):
            m = layer.memory(name="rnn_h", size=H)
            return layer.fc([x_t, m], size=H, act="tanh", name="rnn_h",
                            bias_attr=False)

        group = layer.recurrent_group(step, input=x, name="grp")
        fused_in = layer.fc(x, size=H, act="linear", name="proj",
                            bias_attr=False)
        fused = layer.recurrent(fused_in, act="tanh", name="fused")
        topo = Topology([group, fused])
        params = paddle.parameters.create([group, fused], KeySource(0))

        # tie weights: fused path uses proj.w (input) + fused.w (recurrent)
        vals = dict(params.values)
        vals["proj.w"] = vals["rnn_h.w0"]
        vals["fused.w"] = vals["rnn_h.w1"]

        xs = rng.randn(B, T, F).astype(np.float32)
        lens = np.array([5, 3, 4], np.int32)
        outs, _ = topo.compile()(vals, params.state, {"x": _feed(xs, lens)})
        a, b = np.asarray(outs["grp"].array), np.asarray(outs["fused"].array)
        mask = np.arange(T)[None, :, None] < lens[:, None, None]
        np.testing.assert_allclose(np.where(mask, a, 0), np.where(mask, b, 0),
                                   rtol=1e-5, atol=1e-5)

    def test_memory_boot_and_static_input(self, rng):
        """Memory boots from an outside layer; StaticInput is visible every
        step (reference: memory(boot_layer=...), StaticInput)."""
        B, T, F, H = 2, 4, 3, 3
        x = layer.data("x", paddle.data_type.dense_vector_sequence(F))
        c = layer.data("c", paddle.data_type.dense_vector(H))

        def step(x_t, c_all):
            m = layer.memory(name="acc", size=H, boot_layer=c)
            s = layer.addto([m, c_all], name="acc", act="linear",
                            bias_attr=False)
            return s

        group = layer.recurrent_group(
            step, input=[x, layer.StaticInput(c)], name="g2")
        topo = Topology(group)
        params = paddle.parameters.create(group, KeySource(0))
        xs = rng.randn(B, T, F).astype(np.float32)
        cs = rng.randn(B, H).astype(np.float32)
        lens = np.array([4, 2], np.int32)
        outs, _ = topo.compile()(params.values, params.state,
                                 {"x": _feed(xs, lens), "c": _feed(cs)})
        got = np.asarray(outs["g2"].array)
        # step t: acc = boot + (t+1)*c  => at t=0: 2c, t=1: 3c...
        for t in range(4):
            np.testing.assert_allclose(got[0, t], (t + 2) * cs[0], rtol=1e-5)

    def test_reverse_group(self, rng):
        """reverse=True runs the scan backwards over the valid region."""
        B, T, F = 2, 4, 3
        x = layer.data("x", paddle.data_type.dense_vector_sequence(F))

        def step(x_t):
            m = layer.memory(name="cum", size=F)
            return layer.addto([x_t, m], name="cum", act="linear",
                               bias_attr=False)

        group = layer.recurrent_group(step, input=x, reverse=True, name="g3")
        last = layer.first_seq(group, name="suffix_sum")
        topo = Topology(last)
        params = paddle.parameters.create(last, KeySource(0))
        xs = rng.randn(B, T, F).astype(np.float32)
        lens = np.array([4, 2], np.int32)
        outs, _ = topo.compile()(params.values, params.state,
                                 {"x": _feed(xs, lens)})
        got = np.asarray(outs["suffix_sum"].array)
        # reverse cumulative sum: position 0 holds the total of the valid region
        np.testing.assert_allclose(got[0], xs[0, :4].sum(0), rtol=1e-5)
        np.testing.assert_allclose(got[1], xs[1, :2].sum(0), rtol=1e-5)

    def test_gradients_flow(self, rng):
        B, T, F, H = 2, 3, 4, 5
        x = layer.data("x", paddle.data_type.dense_vector_sequence(F))
        lbl = layer.data("y", paddle.data_type.integer_value(3))

        def step(x_t):
            m = layer.memory(name="h", size=H)
            return layer.fc([x_t, m], size=H, act="tanh", name="h")

        group = layer.recurrent_group(step, input=x, name="g4")
        out = layer.fc(layer.last_seq(group), size=3, act="softmax",
                       name="out")
        cost = layer.classification_cost(out, lbl)
        topo = Topology(cost)
        params = paddle.parameters.create(cost, KeySource(0))
        fwd = topo.compile()
        xs = jnp.asarray(rng.randn(B, T, F).astype(np.float32))
        lens = jnp.asarray(np.array([3, 2], np.int32))
        ys = jnp.asarray(np.array([0, 2], np.int32))

        def loss(p):
            outs, _ = fwd(p, params.state,
                          {"x": Value(xs, lens), "y": Value(ys)})
            return jnp.mean(outs[cost.name].array)

        g = jax.grad(loss)(params.values)
        for k in ("h.w0", "h.w1", "h.b"):
            assert np.all(np.isfinite(np.asarray(g[k])))
            assert np.abs(np.asarray(g[k])).max() > 0


class TestBeamSearchOp:
    def _markov_step(self, M):
        """State-free step: logp of next token depends only on last token."""
        logM = jnp.log(jnp.asarray(M, jnp.float32))

        def step_fn(last, state):
            return logM[last], state
        return step_fn

    def test_greedy_matches_manual_rollout(self):
        V, eos = 4, 0
        rng = np.random.RandomState(0)
        M = rng.dirichlet(np.ones(V), size=V)
        tok, lens, sc = ops_beam.greedy_search(
            self._markov_step(M), {}, batch=1, vocab=V, bos_id=1, eos_id=eos,
            max_len=6)
        # manual rollout
        cur, out = 1, []
        for _ in range(6):
            cur = int(np.argmax(M[cur]))
            out.append(cur)
            if cur == eos:
                break
        got = list(np.asarray(tok[0])[:int(lens[0])])
        assert got == out

    def test_scores_are_true_logprobs(self):
        V, eos, K = 4, 0, 3
        rng = np.random.RandomState(1)
        M = rng.dirichlet(np.ones(V), size=V)
        tok, lens, sc = ops_beam.beam_search(
            self._markov_step(M), {}, batch=2, beam_size=K, vocab=V,
            bos_id=1, eos_id=eos, max_len=5)
        tok, lens, sc = map(np.asarray, (tok, lens, sc))
        for b in range(2):
            for k in range(K):
                seq = tok[b, k, :lens[b, k]]
                prev, total = 1, 0.0
                for t in seq:
                    total += np.log(M[prev, t])
                    prev = int(t)
                np.testing.assert_allclose(sc[b, k], total, rtol=1e-4,
                                           atol=1e-4)
            # sorted best-first
            assert np.all(np.diff(sc[b]) <= 1e-6)

    def test_beam_finds_delayed_reward_path(self):
        """Beam > 1 must beat greedy on a trap: token 2 looks worse now but
        leads to a much better continuation."""
        eos = 0
        # from bos(1): p(2)=0.45, p(3)=0.55 ; from 3: everything mediocre;
        # from 2: p(eos)=0.99
        M = np.array([
            [1.00, 0.00, 0.00, 0.00],   # eos absorbing
            [0.05, 0.00, 0.45, 0.50],   # bos
            [0.99, 0.005, 0.0025, 0.0025],
            [0.30, 0.30, 0.20, 0.20],
        ])
        tok, lens, sc = ops_beam.beam_search(
            self._markov_step(M), {}, batch=1, beam_size=3, vocab=4,
            bos_id=1, eos_id=eos, max_len=4)
        best = list(np.asarray(tok[0, 0])[:int(np.asarray(lens)[0, 0])])
        assert best == [2, 0]  # 0.45*0.99 beats any path through 3

    def test_state_gather_by_parent(self):
        """Recurrent state must follow its beam through reordering: a
        counter state accumulating emitted tokens must equal the returned
        prefix sums."""
        V, eos, K = 4, 0, 2
        rng = np.random.RandomState(2)
        M = rng.dirichlet(np.ones(V) * 2, size=V)
        logM = jnp.log(jnp.asarray(M, jnp.float32))

        def step_fn(last, state):
            return logM[last], {"sum": state["sum"] + last[..., None]}

        init = {"sum": jnp.zeros((1, K, 1), jnp.int32)}
        tok, lens, sc = ops_beam.beam_search(
            step_fn, init, batch=1, beam_size=K, vocab=V, bos_id=1,
            eos_id=eos, max_len=4)
        # state sum should equal bos + sum(tokens before last step)... we
        # can't read final state back; instead just assert determinism and
        # valid shapes — the real state check happens in the layer test below
        assert tok.shape == (1, K, 4)


class TestBeamSearchLayer:
    def test_generation_layer(self, rng):
        """Encoder context → beam_search decoder layer with a GRU-style
        memory; checks shapes, score ordering and eos termination."""
        V, E, H, B = 6, 4, 5, 2
        src = layer.data("src", paddle.data_type.dense_vector(H))

        def step(emb_t):
            m = layer.memory(name="dec_h", size=H, boot_layer=src)
            h = layer.fc([emb_t, m], size=H, act="tanh", name="dec_h")
            return layer.fc(h, size=V, act="softmax", name="dist")

        gen = layer.beam_search(
            step,
            input=[layer.GeneratedInput(size=V, embedding_name="word_emb",
                                        embedding_size=E)],
            bos_id=1, eos_id=0, beam_size=3, max_length=5, name="gen")
        topo = Topology(gen)
        params = paddle.parameters.create(gen, KeySource(0))
        assert "word_emb" in params.values
        fwd = jax.jit(lambda p, s, f: topo.compile()(p, s, f)[0])
        ctxv = rng.randn(B, H).astype(np.float32)
        outs = fwd(params.values, params.state, {"src": _feed(ctxv)})
        v = outs["gen"]
        tok = np.asarray(v.array)
        lens = np.asarray(v.sub_lengths)
        scores = np.asarray(v.weights)
        assert tok.shape == (B, 3, 5)
        assert np.all(np.diff(scores, axis=1) <= 1e-6)
        # all finished sequences end with eos at position len-1
        for b in range(B):
            for k in range(3):
                if lens[b, k] < 5:
                    assert tok[b, k, lens[b, k] - 1] == 0


class TestCrossEntropyOverBeam:
    """Globally-normalized beam training objective (reference:
    CrossEntropyOverBeam.cpp) — fixed-width lattice formulation."""

    def _manual(self, step_scores, parents, gold_scores, gold_slot,
                valid=None):
        """Path enumeration with plain numpy: follow each final slot's
        ancestry, sum selected scores, softmax over paths (+ gold extra
        when fallen off), return -log p(gold)."""
        B, S, K = step_scores.shape
        out = np.zeros((B,), np.float64)
        for b in range(B):
            totals = []
            for k in range(K):
                if valid is not None and not valid[b, k]:
                    continue
                tot, slot = 0.0, k
                for s in range(S - 1, -1, -1):
                    tot += step_scores[b, s, slot]
                    slot = parents[b, s, slot]
                totals.append((k, tot))
            logits = [t for _, t in totals]
            if gold_slot[b] >= 0:
                tgt = [i for i, (k, _) in enumerate(totals)
                       if k == gold_slot[b]][0]
            else:
                logits.append(gold_scores[b].sum())
                tgt = len(logits) - 1
            z = np.asarray(logits, np.float64)
            z = z - z.max()
            p = np.exp(z) / np.exp(z).sum()
            out[b] = -np.log(p[tgt])
        return out

    def _case(self, rng, B=3, S=4, K=3, fall_off=(False, True, False)):
        step_scores = rng.randn(B, S, K).astype(np.float32)
        parents = rng.randint(0, K, (B, S, K)).astype(np.int32)
        gold_scores = rng.randn(B, S).astype(np.float32)
        gold_slot = np.asarray(
            [-1 if f else rng.randint(0, K) for f in fall_off], np.int32)
        return step_scores, parents, gold_scores, gold_slot

    def test_matches_path_enumeration(self, rng):
        args = self._case(rng)
        want = self._manual(*[np.asarray(a, np.float64) if a.dtype.kind == "f"
                              else a for a in args])
        got = ops_beam.cross_entropy_over_beam(
            *[jnp.asarray(a) for a in args])
        np.testing.assert_allclose(np.asarray(got, np.float64), want,
                                   rtol=1e-5, atol=1e-5)

    def test_valid_mask_drops_slots(self, rng):
        step_scores, parents, gold_scores, gold_slot = self._case(
            rng, fall_off=(True, True, False))
        valid = np.ones((3, 3), bool)
        valid[0, 2] = valid[1, 0] = False
        # keep gold_slot consistent with validity
        gold_slot[2] = 1
        want = self._manual(step_scores.astype(np.float64), parents,
                            gold_scores.astype(np.float64), gold_slot, valid)
        got = ops_beam.cross_entropy_over_beam(
            jnp.asarray(step_scores), jnp.asarray(parents),
            jnp.asarray(gold_scores), jnp.asarray(gold_slot),
            jnp.asarray(valid))
        np.testing.assert_allclose(np.asarray(got, np.float64), want,
                                   rtol=1e-5, atol=1e-5)

    def test_numeric_grad(self, rng):
        from tests.op_test_util import check_grad
        step_scores, parents, gold_scores, gold_slot = self._case(
            rng, B=2, S=3, K=2, fall_off=(False, True))

        def fn(sc, gsc):
            return ops_beam.cross_entropy_over_beam(
                sc, jnp.asarray(parents), gsc, jnp.asarray(gold_slot))

        check_grad(fn, [step_scores, gold_scores], wrt=0)
        check_grad(fn, [step_scores, gold_scores], wrt=1)

    def test_layer_surface(self, rng):
        """Flat-feed layer form: the quick path from data layers."""
        import paddle_tpu as paddle
        from paddle_tpu import layer
        from paddle_tpu.topology import Topology, Value
        from paddle_tpu.utils.rng import KeySource
        dt = paddle.data_type
        B, S, K = 2, 3, 2
        sc = layer.data("sc", dt.dense_vector(S * K))
        par = layer.data("par", dt.dense_vector(S * K))
        gsc = layer.data("gsc", dt.dense_vector(S))
        gslot = layer.data("gslot", dt.integer_value(K + 1))
        cost = layer.cross_entropy_over_beam(sc, par, gsc, gslot,
                                             name="beam_ce")
        topo = Topology(cost)
        params = paddle.parameters.create(cost, KeySource(0))
        fwd = topo.compile()
        step_scores, parents, gold_scores, gold_slot = self._case(
            rng, B=B, S=S, K=K, fall_off=(False, True))
        outs, _ = fwd(params.values, params.state, {
            "sc": Value(jnp.asarray(step_scores.reshape(B, S * K))),
            "par": Value(jnp.asarray(parents.reshape(B, S * K))),
            "gsc": Value(jnp.asarray(gold_scores)),
            "gslot": Value(jnp.asarray(gold_slot)),
        })
        want = self._manual(step_scores.astype(np.float64), parents,
                            gold_scores.astype(np.float64), gold_slot)
        np.testing.assert_allclose(
            np.asarray(outs["beam_ce"].array, np.float64), want,
            rtol=1e-5, atol=1e-5)
