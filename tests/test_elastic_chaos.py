"""Kill-a-worker chaos proofs on the multi-process CPU path (slow
lane): a SIGKILL'd gang member is detected, the gang restarts from the
latest intact checkpoint, and the final trajectory equals an
uninterrupted run modulo the re-executed step window; shrinking 4->2
restores a ZeRO checkpoint RESHARDED to the smaller mesh and matches
the same-data 2-host run from the same checkpoint.

Workers are ``demos/elastic_worker.py``: independent single-process
JAX runtimes (jaxlib cannot run cross-process CPU collectives — see
``launch.multiprocess_cpu_supported``) training a bit-deterministic
replicated stream, so trajectory equality is exact, not approximate."""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.io import checkpoint as ckpt_io
from paddle_tpu.runtime.supervisor import Supervisor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "demos", "elastic_worker.py")

pytestmark = pytest.mark.slow


def _clean_env(extra):
    env = dict(os.environ, **{k: str(v) for k, v in extra.items()})
    for k in ("PADDLE_ELASTIC_DIR", "PADDLE_TPU_CHAOS",
              "PADDLE_COORDINATOR"):
        env.pop(k, None)
    return env


def _run_worker_direct(out, nprocs, nb, period=2, rank=0, timeout=300):
    """One un-supervised worker run (the reference trajectory)."""
    env = _clean_env({
        "PADDLE_NUM_PROCESSES": nprocs, "PADDLE_PROCESS_ID": rank,
        "PADDLE_LOCAL_CPU_DEVICES": 4, "PADDLE_ELASTIC_EPOCH": 0,
        "ELASTIC_OUT": out, "ELASTIC_NB": nb,
        "PADDLE_TPU_CHECKPOINT_PERIOD": period})
    subprocess.run([sys.executable, WORKER], env=env, check=True,
                   timeout=timeout)


def _supervise(out, nprocs, nb, chaos, period=2, sleep=0.05, **kw):
    kw.setdefault("heartbeat_window", 30.0)
    kw.setdefault("startup_grace", 180.0)
    kw.setdefault("poll_interval", 0.2)
    kw.setdefault("backoff_base", 0.1)
    kw.setdefault("backoff_cap", 0.5)
    kw.setdefault("max_restarts", 2)
    kw.setdefault("attempt_timeout", 240.0)
    s = Supervisor(
        [WORKER], nprocs=nprocs, state_dir=os.path.join(out, "state"),
        devices_per_proc=4, cluster=False,
        env_extra={"ELASTIC_OUT": out, "ELASTIC_NB": str(nb),
                   "ELASTIC_STEP_SLEEP": str(sleep),
                   "PADDLE_TPU_CHECKPOINT_PERIOD": str(period),
                   "PADDLE_TPU_CHAOS": chaos}, **kw)
    return s, s.run(total_timeout=900)


def _final(out, rank, epoch):
    path = os.path.join(out, f"final_rank{rank}_epoch{epoch}.npz")
    assert os.path.exists(path), sorted(os.listdir(out))
    return dict(np.load(path))


def _losses(out, rank, epoch):
    path = os.path.join(out, f"losses_rank{rank}_epoch{epoch}.jsonl")
    with open(path) as f:
        return {json.loads(ln)["step"]: json.loads(ln)["loss"]
                for ln in f if ln.strip()}


def _assert_params_equal(a, b):
    assert sorted(a) == sorted(b)
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=0, atol=0,
                                   err_msg=k)


class TestKillWorkerMidRun:
    def test_trajectory_equals_uninterrupted_run(self, tmp_path):
        """SIGKILL rank 1 mid-step at step 5 of 12: the supervisor
        detects it, restarts the gang (fresh epoch), training restores
        and completes, and the final params + post-restore loss trail
        are EXACTLY the uninterrupted run's."""
        ref = str(tmp_path / "ref")
        _run_worker_direct(ref, nprocs=2, nb=12)

        out = str(tmp_path / "elastic")
        s, res = _supervise(
            out, nprocs=2, nb=12,
            chaos="kill@step:step=5:rank=1:epoch=1")
        assert res["ok"], res
        assert res["restarts"] == 1
        assert res["attempts"][0]["reason"].startswith("worker_exit")
        assert res["attempts"][0]["failed_ranks"] == [1]
        # the restart left a flight post-mortem
        assert os.listdir(os.path.join(out, "state", "flight"))

        final_epoch = res["epoch"]
        assert final_epoch == 2
        for rank in (0, 1):
            done = json.load(open(os.path.join(
                out, f"done_rank{rank}_epoch{final_epoch}.json")))
            assert done["step"] == 12
            _assert_params_equal(_final(out, rank, final_epoch),
                                 _final(ref, 0, 0))
        # loss trail: every step the restarted incarnation executed
        # matches the uninterrupted run bit-for-bit (the re-executed
        # window is part of the overlap — determinism makes it equal)
        ref_losses = _losses(ref, 0, 0)
        got = _losses(out, 0, final_epoch)
        assert got, "restarted incarnation logged no steps"
        assert max(got) == 11                   # ran through the end
        for step, loss in got.items():
            np.testing.assert_allclose(loss, ref_losses[step], rtol=0,
                                       atol=0, err_msg=f"step {step}")


class TestShrinkFourToTwo:
    def test_resharded_resume_matches_two_host_run(self, tmp_path):
        """4-worker gang loses rank 3 with no replacement: the gang
        degrades to 2 (valid_sizes snap), every survivor restores the
        step-4 ZeRO checkpoint written under data=4 RESHARDED into
        data=2 (meta-driven), and the continued trajectory equals a
        plain 2-host run resumed from the very same checkpoint."""
        seed = str(tmp_path / "seed")
        _run_worker_direct(seed, nprocs=4, nb=4)   # checkpoint @ step 4
        seed_ck = os.path.join(seed, "ckpt_rank0")
        latest = ckpt_io.latest_checkpoint(seed_ck)
        assert latest.endswith("ckpt-00000004")
        meta = ckpt_io.checkpoint_meta(latest)
        assert meta["zero"]["axis_size"] == 4      # the layout to reshard

        out = str(tmp_path / "elastic")
        ref = str(tmp_path / "ref")
        for rank in range(4):
            shutil.copytree(seed_ck, os.path.join(out, f"ckpt_rank{rank}"))
        shutil.copytree(seed_ck, os.path.join(ref, "ckpt_rank0"))

        # reference: a plain 2-host run resumed from the same checkpoint
        # (period=100: neither scenario writes a new checkpoint before
        # the kill, so both resume from exactly step 4)
        _run_worker_direct(ref, nprocs=2, nb=10, period=100)

        s, res = _supervise(
            out, nprocs=4, nb=10, period=100,
            chaos="kill@step:step=5:rank=3:epoch=1",
            replacements=0, valid_sizes=[4, 2], min_nprocs=2)
        assert res["ok"], res
        assert res["restarts"] == 1
        assert res["attempts"][1]["nprocs"] == 2   # 4 -> 2
        final_epoch = res["epoch"]
        for rank in (0, 1):
            done = json.load(open(os.path.join(
                out, f"done_rank{rank}_epoch{final_epoch}.json")))
            assert done["step"] == 10 and done["nprocs"] == 2
            # every survivor's replicated-compute trajectory equals the
            # reference's (ranks are identical by construction)
            _assert_params_equal(_final(out, rank, final_epoch),
                                 _final(ref, 0, 0))
        # post-restore losses equal the 2-host reference's exactly
        got = _losses(out, 0, final_epoch)
        ref_losses = _losses(ref, 0, 0)
        assert got and min(got) >= 4               # resumed, not restarted
        for step, loss in got.items():
            np.testing.assert_allclose(loss, ref_losses[step], rtol=0,
                                       atol=0, err_msg=f"step {step}")
