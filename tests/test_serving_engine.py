"""Continuous-batching decode engine: per-slot positions must be
bitwise-faithful to lockstep decode, admission/recycling must not
perturb in-flight slots, sampling runs on device, and the whole engine
compiles once per prefill bucket + once for decode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models import transformer
from paddle_tpu.observe.compile_tracker import CompileTracker
from paddle_tpu.serving import DecodeEngine, sample_tokens

CFG = transformer.TransformerConfig(
    vocab=40, d_model=16, n_heads=2, n_kv_heads=1, n_layers=2, d_ff=32,
    max_len=64, dtype=jnp.float32, use_rope=True)
CFG_ABS = transformer.TransformerConfig(
    vocab=40, d_model=16, n_heads=2, n_layers=2, d_ff=32,
    max_len=64, dtype=jnp.float32, use_rope=False)
PARAMS = transformer.init_params(jax.random.PRNGKey(0), CFG)


def _engine(batch=2, cache_len=32, buckets=(8, 16), seed=0,
            params=PARAMS, cfg=CFG):
    return DecodeEngine.from_params(
        params, cfg, batch=batch, cache_len=cache_len, buckets=buckets,
        seed=seed, tracker=CompileTracker())


class TestSlotDecodeKernels:
    @pytest.mark.parametrize("cfg", [CFG, CFG_ABS],
                             ids=["rope", "learned-pos"])
    def test_vector_pos_decode_bitwise_matches_lockstep(self, cfg, rng):
        """Aligned positions: decode_step_slots == decode_step bitwise
        (logits AND cache), for both position encodings."""
        params = transformer.init_params(jax.random.PRNGKey(0), cfg)
        B, Tp = 3, 6
        prompt = jnp.asarray(rng.randint(0, 40, (B, Tp)), jnp.int32)
        logits, cache = transformer.prefill(params, prompt, cfg, 20)
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        l_lock, c_lock = transformer.decode_step(
            params, cache, tok, jnp.asarray(Tp, jnp.int32), cfg)
        l_slot, c_slot = transformer.decode_step_slots(
            params, cache, tok, jnp.full((B,), Tp, jnp.int32),
            jnp.ones((B,), bool), cfg)
        np.testing.assert_array_equal(np.asarray(l_lock),
                                      np.asarray(l_slot))
        for leaf in ("k", "v"):
            np.testing.assert_array_equal(np.asarray(c_lock[leaf]),
                                          np.asarray(c_slot[leaf]))

    def test_inactive_slots_not_written(self, rng):
        """active=False rows keep their cache bitwise intact and rows
        never cross-write (each row targets its own position)."""
        B, Tp = 3, 6
        prompt = jnp.asarray(rng.randint(0, 40, (B, Tp)), jnp.int32)
        _, cache = transformer.prefill(PARAMS, prompt, CFG, 20)
        tok = jnp.zeros((B,), jnp.int32)
        active = jnp.asarray([True, False, True])
        _, c2 = transformer.decode_step_slots(
            PARAMS, cache, tok, jnp.asarray([6, 3, 9], jnp.int32),
            active, CFG)
        # row 1 untouched everywhere
        np.testing.assert_array_equal(np.asarray(cache["k"][:, 1]),
                                      np.asarray(c2["k"][:, 1]))
        # row 0 wrote position 6 only; row 2 wrote position 9 only
        k0, k2 = np.asarray(c2["k"][:, 0]), np.asarray(c2["k"][:, 2])
        k0_ref = np.asarray(cache["k"][:, 0])
        assert not np.array_equal(k0[:, 6], k0_ref[:, 6])
        np.testing.assert_array_equal(k0[:, 7:], k0_ref[:, 7:])
        np.testing.assert_array_equal(k2[:, 6:9],
                                      np.asarray(cache["k"][:, 2, 6:9]))
        assert not np.array_equal(k2[:, 9],
                                  np.asarray(cache["k"][:, 2, 9]))

    def test_prefill_into_slot_matches_batched_prefill(self, rng):
        """Right-padded slot prefill reproduces the unpadded lockstep
        prefill logits bitwise and leaves other arena rows zero."""
        B, Tp, cache_len = 3, 6, 24
        prompt = jnp.asarray(rng.randint(0, 40, (B, Tp)), jnp.int32)
        logits, _ = transformer.prefill(PARAMS, prompt, CFG, cache_len)
        arena = transformer.init_cache(CFG, B, cache_len)
        padded = jnp.pad(prompt[1:2], ((0, 0), (0, 2)))   # bucket 8
        lg, arena = transformer.prefill_into_slot(
            PARAMS, arena, padded, jnp.asarray(Tp, jnp.int32),
            jnp.asarray(1, jnp.int32), CFG)
        np.testing.assert_array_equal(np.asarray(lg[0]),
                                      np.asarray(logits[1]))
        np.testing.assert_array_equal(np.asarray(arena["k"][:, 0]), 0.0)
        np.testing.assert_array_equal(np.asarray(arena["k"][:, 2]), 0.0)


class TestOnDeviceSampling:
    def test_greedy_rows_argmax(self, rng):
        logits = jnp.asarray(rng.randn(4, 12), jnp.float32)
        out = sample_tokens(logits, jax.random.PRNGKey(0),
                            jnp.zeros(4), jnp.zeros(4, jnp.int32))
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(logits).argmax(-1))

    def test_top_k_restricts_support(self, rng):
        """With top_k=k, samples only ever land in the k largest."""
        logits = jnp.asarray(rng.randn(2, 20), jnp.float32)
        top3 = np.argsort(np.asarray(logits), -1)[:, -3:]
        for s in range(20):
            out = np.asarray(sample_tokens(
                logits, jax.random.PRNGKey(s),
                jnp.full(2, 1.5), jnp.full(2, 3, jnp.int32)))
            for row in range(2):
                assert out[row] in top3[row]

    def test_mixed_greedy_and_sampled_rows(self, rng):
        logits = jnp.asarray(rng.randn(2, 12), jnp.float32)
        out = np.asarray(sample_tokens(
            logits, jax.random.PRNGKey(3),
            jnp.asarray([0.0, 5.0]), jnp.zeros(2, jnp.int32)))
        assert out[0] == np.asarray(logits[0]).argmax()


class TestEngineScheduling:
    def test_engine_matches_lockstep_generate(self, rng):
        """Greedy engine output == transformer.generate per request,
        with mixed prompt lengths sharing the arena."""
        eng = _engine()
        prompts = [rng.randint(0, 40, n).astype(np.int32)
                   for n in (5, 9, 3)]
        reqs = [eng.submit(p, max_new=6) for p in prompts]
        done = eng.run_until_idle()
        assert len(done) == 3
        for r, p in zip(reqs, prompts):
            want = np.asarray(transformer.generate(
                PARAMS, jnp.asarray(p[None]), CFG, max_new=6))[0]
            np.testing.assert_array_equal(r.output, want)
            assert r.finish_reason == "max_tokens"

    def test_mid_flight_admission_does_not_perturb(self, rng):
        """The continuous-batching invariant: a request admitted into a
        free slot changes NOTHING for its in-flight neighbour."""
        pa = rng.randint(0, 40, 5).astype(np.int32)
        pb = rng.randint(0, 40, 9).astype(np.int32)
        solo = _engine()
        ra_solo = solo.submit(pa, max_new=8)
        solo.run_until_idle()

        eng = _engine()
        ra = eng.submit(pa, max_new=8)
        for _ in range(3):
            eng.step()              # A mid-flight with 4 tokens
        assert len(ra.tokens) == 4
        rb = eng.submit(pb, max_new=6)   # joins slot 1 mid-flight
        eng.run_until_idle()
        np.testing.assert_array_equal(ra.output, ra_solo.output)
        want_b = np.asarray(transformer.generate(
            PARAMS, jnp.asarray(pb[None]), CFG, max_new=6))[0]
        np.testing.assert_array_equal(rb.output, want_b)

    def test_eos_recycles_slot_for_queued_request(self, rng):
        """EOS termination frees the slot; the queued request fills it
        and decodes correctly in the recycled row."""
        pa = rng.randint(0, 40, 5).astype(np.int32)
        pc = rng.randint(0, 40, 7).astype(np.int32)
        probe = _engine(batch=1)
        ra = probe.submit(pa, max_new=8)
        probe.run_until_idle()
        # pick an eos that first appears mid-stream (greedy stream is
        # deterministic, so the replay terminates exactly there)
        idx = next(i for i in range(1, len(ra.tokens))
                   if ra.tokens[i] not in ra.tokens[:i])
        eos = ra.tokens[idx]

        eng = _engine(batch=1)      # one slot: C must wait for A's EOS
        ra2 = eng.submit(pa, max_new=8, eos_id=eos)
        rc = eng.submit(pc, max_new=4)
        assert eng.queue_depth == 2          # admission happens in step()
        eng.step()
        assert rc.status == "queued"         # arena full until A's EOS
        eng.run_until_idle()
        assert ra2.finish_reason == "eos"
        assert ra2.tokens == ra.tokens[:idx + 1]  # stops AT the eos
        assert rc.slot == 0 and rc.finish_reason == "max_tokens"
        want_c = np.asarray(transformer.generate(
            PARAMS, jnp.asarray(pc[None]), CFG, max_new=4))[0]
        np.testing.assert_array_equal(rc.output, want_c)

    def test_compile_once_per_bucket_plus_decode(self, rng):
        """The static-shape contract: N distinct prompt buckets compile
        N prefills; every decode step shares ONE compilation."""
        eng = _engine(batch=2, buckets=(8, 16, 32))
        for n in (3, 5, 12, 7, 15, 2):      # buckets 8 and 16 only
            eng.submit(rng.randint(0, 40, n).astype(np.int32),
                       max_new=4)
        eng.run_until_idle()
        assert eng.compile_counts() == {"prefill": 2, "decode": 1}

    def test_submit_guards(self, rng):
        eng = _engine(cache_len=16, buckets=(8,))
        with pytest.raises(ValueError, match="exceed cache_len"):
            eng.submit(rng.randint(0, 40, 8), max_new=16)
        with pytest.raises(ValueError, match="largest prefill bucket"):
            eng.submit(rng.randint(0, 40, 12), max_new=2)
        with pytest.raises(ValueError, match="max_new"):
            eng.submit(rng.randint(0, 40, 4), max_new=0)

    def test_unseeded_engines_differ(self, rng):
        """seed=None engines must not replay one sampling stream."""
        prompt = rng.randint(0, 40, 5).astype(np.int32)
        outs = []
        for _ in range(2):
            eng = _engine(seed=None)
            r = eng.submit(prompt, max_new=12, temperature=100.0)
            eng.run_until_idle()
            outs.append(list(r.tokens))
        assert outs[0] != outs[1]


class TestEngineObservability:
    def test_metrics_and_health_endpoint(self, rng):
        import json as _json
        import urllib.request
        eng = _engine()
        for n in (5, 9, 3):
            eng.submit(rng.randint(0, 40, n).astype(np.int32), max_new=4)
        eng.run_until_idle()
        assert eng.metrics.get("engine_tokens_total").value() == 12
        assert eng.metrics.get(
            "engine_ttft_seconds").snapshot()["count"] == 3
        assert eng.metrics.get(
            "engine_requests_completed_total").value(
                reason="max_tokens") == 3
        assert eng.metrics.get("engine_slots_active").value() == 0
        text = eng.metrics_text()
        assert "# TYPE engine_queue_wait_seconds histogram" in text
        assert "engine_request_tokens_per_sec_bucket" in text
        http = eng.serve()
        try:
            health = _json.loads(urllib.request.urlopen(
                http.url + "/healthz", timeout=5).read())
            assert health["status"] == "ok"
            assert health["completed"] == 3 and health["tokens"] == 12
            scraped = urllib.request.urlopen(
                http.url + "/metrics", timeout=5).read().decode()
            assert "engine_tokens_total 12" in scraped
        finally:
            http.close()


class TestServingBenchSmoke:
    def test_bench_smoke_engine_beats_nothing_but_runs(self):
        """Tier-1 exercise of the full bench path (--smoke): all three
        variants (paged / row-arena / lockstep) produce sane numbers on
        a shared-prefix + long-prompt-adversarial trace and the compile
        invariants (asserted inside the runners) hold. The paged-wins
        throughput/TTFT claims are the full-size run's, not the toy's."""
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "serving_bench_under_test",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
                "benchmarks", "serving_bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        trace_out = os.path.join(os.environ.get("TMPDIR", "/tmp"),
                                 f"req_trace_{os.getpid()}.json")
        try:
            results = mod.main(["--smoke", f"--trace-out={trace_out}"])
        finally:
            if os.path.exists(trace_out):
                os.remove(trace_out)
        # throughput phase: the 6 Poisson requests; latency phase adds
        # 1 adversarial long prompt
        tp, lat = results["throughput"], results["latency"]
        assert tp["engine_paged"]["requests"] == 6
        assert lat["engine_paged"]["requests"] == 7
        for phase in (tp, lat):
            assert phase["engine_paged"]["tokens"] == \
                phase["engine_slots"]["tokens"] == \
                phase["lockstep"]["tokens"]
            assert phase["engine_paged"]["tokens_per_sec"] > 0
            assert phase["engine_paged"]["compiles"]["decode"] == 1
            assert phase["engine_slots"]["compiles"]["decode"] == 1
            # the shared-prefix half of the trace hit the prefix cache
            assert phase["engine_paged"]["prefix_hit_blocks"] >= 1
            assert phase["engine_paged"]["blocks_in_use_peak"] <= \
                phase["engine_paged"]["blocks_total"]
        assert results["serving_paged_speedup"] > 0
        assert results["serving_paged_ttft_p99_ratio"] > 0
        # flash-decode-era fields: decode MFU reported per engine, the
        # int8 variant rode the throughput phase token-for-token, and
        # the interpret-mode kernel matched the XLA engine's ids
        assert tp["engine_paged"]["decode_mfu"] is not None
        assert tp["engine_paged_int8"]["tokens"] == \
            tp["engine_paged"]["tokens"]
        assert results["serving_int8_speedup"] > 0
        assert results["pallas"]["interpret_check_ok"] is True
        # KV-quantization era fields: the int8-KV pool variant rode
        # the throughput phase token-for-token at ~1/3 the bytes, the
        # quantized interpret check (fused dequant, decode + chunked
        # prefill) held, capacity shows >= 2x slots at equal HBM, and
        # the cold-prefill / quality scoreboards materialized
        assert tp["engine_paged_kv8"]["tokens"] == \
            tp["engine_paged"]["tokens"]
        assert tp["engine_paged_kv8"]["kv_dtype"] == "int8"
        assert tp["engine_paged_kv8"]["kv_bytes_per_token"] < \
            tp["engine_paged"]["kv_bytes_per_token"]
        assert results["serving_kv8_speedup"] > 0
        assert results["pallas"]["interpret_check_kv8_ok"] is True
        cap = results["capacity"]
        assert cap["slots_int8_ge_2x_fp32"] is True
        assert cap["slots_at_equal_hbm_int8"] >= \
            2 * cap["slots_at_equal_hbm_fp32"]
        assert cap["slots_at_equal_hbm_int4"] >= \
            cap["slots_at_equal_hbm_int8"]
        assert results["cold_prefill"]["ttft_p50_cold_ms"] > 0
        q = results["quality"]
        assert 0 < q["kv_int8_rel_l2"] < q["kv_int8_rel_l2_budget"]
        assert 0 < q["kv_int4_rel_l2"] < q["kv_int4_rel_l2_budget"]
        # per-request attribution replay: every request attributed
        # (the joined-lifecycle invariant is asserted INSIDE the bench
        # when --trace-out is given — reaching here means it held)
        attr = results["attribution"]
        assert attr["requests"] == 7
        assert len(attr["slowest_by_ttft"]) == 7
        comps = attr["slowest_by_ttft"][0]["attribution"]["components"]
        assert set(comps) == {"queue_wait_s", "prefill_own_s",
                              "prefill_stall_s", "decode_s"}
        assert attr["victims"]["count"] >= 1
        assert attr["victims"]["adversary_prompt_tokens"] == 56
        # multi-tenant + spec-decode era fields: both phases ran under
        # --smoke (the tiered/FIFO A/B completed leak-free with both
        # tiers represented, and the spec phase's bitwise-greedy +
        # compile-discipline asserts — checked INSIDE the phase —
        # held; the speedup/separation CLAIMS are the full run's)
        mt = results["multitenant"]
        assert mt["tiered"]["requests_latency"] >= 1
        assert mt["tiered"]["requests_batch"] >= 1
        assert mt["fifo"]["tokens_per_sec"] > 0
        sd = results["spec_decode"]
        assert sd["greedy_bitwise_ok"] is True
        assert sd["acceptance_rate"] is not None
        assert sd["spec_tokens_per_sec"] > 0
        assert results["spec_decode_speedup"] > 0
        # serving-fleet era fields: the router A/B ran under --smoke
        # with zero lost requests and the P/D disaggregation bitwise
        # check (asserted INSIDE the phase) held; the goodput /
        # victim-TTFT CLAIMS are the dedicated --fleet run's
        fl = results["fleet"]
        assert fl["all_requests_completed"] is True
        assert fl["pd_bitwise_ok"] is True
        assert fl["fleet"]["requeued"] == 0
        assert fl["fleet"]["tokens_per_sec"] > 0
        assert fl["pd_blocks_shipped"] >= 1

    def test_bench_smoke_fleet_chaos_phase(self):
        """Tier-1 exercise of the control-plane chaos path (--smoke
        --fleet-chaos): the kill fires at the peak, the controller
        heals the fleet back to full capacity, and every admitted
        request completes. The TTFT-band / shed / rewarm CLAIMS are
        the dedicated full-size run's (the fleet sentinel family)."""
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "serving_bench_chaos_under_test",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
                "benchmarks", "serving_bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        results = mod.main(["--smoke", "--fleet-chaos"])
        fc = results["fleet_chaos"]
        assert fc["controlled"]["killed_replica"] is not None
        assert fc["healed_capacity_frac"] == 1.0
        assert fc["recovery_s"] is not None and fc["recovery_s"] > 0
        assert fc["all_admitted_completed"] is True
        assert fc["controlled"]["completed"] == \
            fc["controlled"]["requests"]
        assert fc["static"]["completed"] == fc["static"]["requests"]
