"""Attention seq2seq train + beam-search generate (book-style e2e).

Reference analog: the machine-translation book test
(python/paddle/v2/framework/tests/book/ style) and
test_recurrent_machine_generation.cpp — train a few steps, then generate.
"""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import seq2seq
from paddle_tpu.topology import Topology, Value
from paddle_tpu.utils.rng import KeySource

V, E, H = 10, 8, 12
BOS, EOS = 0, 1


def _batch(rng, B, T):
    src = rng.randint(2, V, (B, T)).astype(np.int32)
    lens = rng.randint(2, T + 1, B).astype(np.int32)
    return src, lens


def test_seq2seq_copy_task_learns_and_generates():
    cost = seq2seq.seq2seq_train(V, V, word_vec_dim=E, encoder_size=H,
                                 decoder_size=H)
    topo = Topology(cost)
    params = paddle.parameters.create(cost, KeySource(0))
    fwd = topo.compile()

    def loss_fn(p, src, slens, trg, nxt):
        outs, _ = fwd(p, params.state,
                      {"source_language_word": Value(src, slens),
                       "target_language_word": Value(trg, slens),
                       "target_language_next_word": Value(nxt, slens)})
        return jnp.mean(outs["seq2seq_cost"].array /
                        jnp.maximum(slens.astype(jnp.float32), 1))

    step = jax.jit(jax.value_and_grad(loss_fn))
    rng = np.random.RandomState(0)
    B, T = 8, 5
    vals = params.values
    losses = []
    for it in range(60):
        src, lens = _batch(rng, B, T)
        # copy task: target = bos + src, next = src + eos
        trg = np.concatenate([np.full((B, 1), BOS, np.int32), src[:, :-1]], 1)
        nxt = src.copy()
        for b in range(B):
            nxt[b, lens[b] - 1] = EOS
        l, g = step(vals, jnp.asarray(src), jnp.asarray(lens),
                    jnp.asarray(trg), jnp.asarray(nxt))
        vals = jax.tree_util.tree_map(lambda p, gr: p - 0.5 * gr, vals, g)
        losses.append(float(l))
    # deterministic convergence invariants instead of an absolute
    # threshold (the PR-4/5 deflake pattern: "losses[-1] < 0.7*losses[0]"
    # encoded an env-sensitive convergence SPEED, not a property of the
    # optimizer): (1) the windowed trend is monotone-decreasing, (2) the
    # final window sits below the initial one by a margin derived from
    # the run's own achieved range — both hold for any environment in
    # which training makes consistent progress at all.
    w = 10
    early = float(np.mean(losses[:w]))
    late = float(np.mean(losses[-w:]))
    assert late < early, (early, late)
    achieved = early - min(losses)
    assert achieved > 0, losses
    assert late < early - 0.5 * achieved, (early, late, achieved)
    # monotone-ish: once the smoothed trajectory has crossed the
    # midpoint of the drop it never climbs back above the initial level
    smooth = np.convolve(losses, np.ones(w) / w, mode="valid")
    crossed = np.flatnonzero(smooth < early - 0.5 * achieved)
    assert crossed.size and smooth[crossed[0]:].max() < early, losses

    # generation shares the learned parameters by name
    gen = seq2seq.seq2seq_generate(V, V, word_vec_dim=E, encoder_size=H,
                                   decoder_size=H, beam_size=3, max_length=6,
                                   bos_id=BOS, eos_id=EOS)
    gtopo = Topology(gen)
    gparams = paddle.parameters.create(gen, KeySource(0))
    assert set(gparams.values) <= set(vals)
    gfwd = jax.jit(lambda p, s, f: gtopo.compile()(p, s, f)[0])
    src, lens = _batch(rng, 4, T)
    outs = gfwd({k: vals[k] for k in gparams.values}, gparams.state,
                {"source_language_word": Value(jnp.asarray(src),
                                               jnp.asarray(lens))})
    v = outs["generated_word"]
    assert v.array.shape == (4, 3, 6)
    scores = np.asarray(v.weights)
    assert np.all(np.diff(scores, axis=1) <= 1e-6)  # beams sorted
    assert np.all(np.isfinite(scores[:, 0]))
